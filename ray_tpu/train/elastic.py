"""Elastic gang training: resize-in-place on preemptible capacity.

The fixed-world train path (trainer.py) treats a preempted worker as a
restart: kill the gang, restore from the last DISK checkpoint at the
same world size, and wait for replacement hardware.  On preemptible
fleets that wait can be minutes of dead time.  This module decouples
the job from its hardware (the VirtualFlow virtual-node idea) and
reshards optimizer state across survivors (ZeRO-style sharded state):

1. **In-cluster sharded checkpoints** — each worker asynchronously
   snapshots ITS shard of params/opt_state into the object store on a
   cadence (``train_ckpt_interval_s``).  A per-run *checkpoint keeper*
   actor collects the shard ObjectRefs and, once every member's shard
   for a step has arrived, registers a manifest (run, step, mesh
   shape, shard -> ObjectRef map) in the control-plane KV — so the
   latest CONSISTENT step is discoverable after any failure.

   Ref-pinning contract (the PR-4 "last borrow drops the replica"
   trap): the keeper is the live owner pinning every committed shard;
   an old manifest's blocks are released only AFTER the new manifest
   is registered, and the publishing worker keeps its own put refs
   alive across the handoff so the keeper's borrow always lands on a
   live entry.

2. **Resize on preemption** — when a ``preempt`` notice (or a hard
   kill) removes a worker, the driver bumps the gang *epoch* in the
   gang record; survivors observe the epoch change at their next
   ``sync()``, pull the missing shards from the in-cluster checkpoint
   (ZERO disk reads — counted by the telemetry ckpt-read accounting),
   reshard to the new world size, and continue at reduced throughput.

3. **Grow-back** — when capacity heals the driver spawns a
   replacement worker (telemetry ``recovery_class="resize_recovery"``)
   and bumps the epoch again; resharding runs in reverse.

4. **Accounting** — resize dead time is charged to the goodput
   ledger's ``resize_recovery`` class (distinct from
   ``restart_recovery``), ``ray_tpu_train_resizes_total{direction}`` /
   ``ray_tpu_train_world_size`` move, resize events surface in
   ``state.train_summary()`` / ``ray_tpu train status``, and
   ``state.doctor()`` flags GANG_RESIZE_THRASH when the resize rate
   crosses ``train_resize_thrash_per_min``.

Enable with ``train_elastic_enabled`` (or
``ScalingConfig(elastic=True)``).  The worker-side surface is
``session.get_context().elastic()`` -> :class:`ElasticSession`.
"""

from __future__ import annotations

import json
import pickle
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu._private.config import config
from ray_tpu.devtools import leaksan

# Control-plane KV namespaces.
KV_CKPT_NS = "__train_ckpt__"     # run -> pickled shard manifest
KV_GANG_NS = "__train_gang__"     # run -> json gang record
KV_REDUCE_NS = "__train_reduce__"  # per-(epoch, step, rank) reduce slots

_SEP = "\x1f"


class ResizeInterrupt(Exception):
    """Raised out of a collective when the gang epoch changed under it
    (a member died or joined); the caller re-syncs, reshards from the
    in-cluster checkpoint, and continues."""


def keeper_name(run: str) -> str:
    """The per-run checkpoint keeper's GCS actor-directory name."""
    return f"elastic_keeper:{run}"


# ---------------------------------------------------------------------------
# pytree shard/reshard helpers (pure, unit-testable)
# ---------------------------------------------------------------------------
def shard_pytree(tree: Any, index: int, nshards: int) -> Any:
    """This shard's slice of a pytree: every array leaf is split along
    axis 0 into ``nshards`` near-equal parts (np.array_split, so any
    leading dim works); 0-d leaves are replicated.  Exact round-trip
    with :func:`unshard_pytree` for ANY nshards — which is what makes
    4 -> 3 -> 4 resharding a pure unshard+reshard."""
    if not 0 <= index < nshards:
        raise ValueError(f"shard index {index} not in [0, {nshards})")
    if isinstance(tree, dict):
        return {k: shard_pytree(v, index, nshards)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(shard_pytree(v, index, nshards)
                          for v in tree)
    arr = np.asarray(tree)
    if arr.ndim == 0:
        return arr
    return np.array_split(arr, nshards, axis=0)[index]


def unshard_pytree(shards: List[Any]) -> Any:
    """Inverse of :func:`shard_pytree`: concatenate the ordered shard
    list back into the full pytree."""
    if not shards:
        raise ValueError("no shards to unshard")
    first = shards[0]
    if isinstance(first, dict):
        return {k: unshard_pytree([s[k] for s in shards])
                for k in first}
    if isinstance(first, (list, tuple)):
        return type(first)(
            unshard_pytree([s[i] for s in shards])
            for i in range(len(first)))
    arr = np.asarray(first)
    if arr.ndim == 0:
        return arr
    parts = [np.asarray(s) for s in shards]
    return np.concatenate([p for p in parts if p.size or p.ndim],
                          axis=0)


def _tree_scale_add(acc: Any, tree: Any, w: float) -> Any:
    """acc + w * tree, leafwise (acc may be None = zero)."""
    if isinstance(tree, dict):
        return {k: _tree_scale_add(None if acc is None else acc[k],
                                   v, w)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(
            _tree_scale_add(None if acc is None else acc[i], v, w)
            for i, v in enumerate(tree))
    leaf = np.asarray(tree, dtype=np.float64) * w
    return leaf if acc is None else acc + leaf


def _tree_scale(tree: Any, s: float) -> Any:
    if isinstance(tree, dict):
        return {k: _tree_scale(v, s) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_scale(v, s) for v in tree)
    return np.asarray(tree) * s


# ---------------------------------------------------------------------------
# manifest store (the keeper's brain; plain class so the ref-pinning
# order is unit-testable in process)
# ---------------------------------------------------------------------------
class ManifestStore:
    """Collects per-member shard refs per step and commits a manifest
    to the control-plane KV once a step is complete.

    Ordering contract (the regression the PR-4 trap demands): a step's
    shard refs are released only AFTER a NEWER manifest has been
    registered in the KV — a reader that resolved the latest manifest
    always finds its blocks pinned by this store.  ``log`` records
    every ("register", step) / ("release", step) transition so tests
    can assert the order outright.

    Epoch freeze (:meth:`freeze`): the first restore request for a
    gang epoch pins that epoch's restore point and drops every
    publish tagged with an older epoch from then on.  Without it, a
    stale pre-resize publish could complete a slot BETWEEN two
    survivors' restores — they'd resume at different steps and the
    KV allreduce would never complete."""

    def __init__(self, run: str, client: Any = None,
                 keep: Optional[int] = None) -> None:
        self.run = run
        self._client = client
        self.keep = max(int(keep if keep is not None
                            else config.train_ckpt_keep), 1)
        # {(step, nshards): {idx: ref}} awaiting completion.
        self._pending: Dict[Tuple[int, int], Dict[int, Any]] = {}
        # Committed steps oldest-first: [(step, {idx: ref}, nshards)].
        self._committed: List[Tuple[int, Dict[int, Any], int]] = []
        self.log: List[Tuple[str, int]] = []
        self.commits = 0
        self.releases = 0
        self._min_epoch = 0
        # {epoch: manifest-or-None} — only the newest epoch is cached.
        self._frozen: Dict[int, Optional[Dict[str, Any]]] = {}

    # -- publish/commit -------------------------------------------------
    def publish(self, step: int, index: int, nshards: int,
                ref: Any, meta: Optional[Dict[str, Any]] = None,
                epoch: int = 0) -> Optional[int]:
        """Record one member's shard for (step, nshards).  Returns the
        step just committed when this shard completed it, else None.
        Recomputed steps at or below the latest commit (post-resize
        rollback replay) and publishes from a pre-freeze epoch are
        ignored."""
        step = int(step)
        if int(epoch) < self._min_epoch:
            return None
        latest = self.latest_step()
        if latest is not None and step <= latest:
            return None
        slot = self._pending.setdefault((step, int(nshards)), {})
        old = slot.get(int(index))
        slot[int(index)] = ref
        if old is None:
            leaksan.register("ckpt_shard",
                            (self.run, step, int(nshards), int(index)),
                            detail=f"elastic shard {self.run} s{step}")
        if len(slot) == int(nshards):
            self._commit(step, int(nshards), meta or {})
            return step
        return None

    def _commit(self, step: int, nshards: int,
                meta: Dict[str, Any]) -> None:
        shards = self._pending.pop((step, nshards))
        manifest = {
            "run": self.run,
            "step": step,
            "world_size": nshards,
            "mesh_shape": list(meta.get("mesh_shape") or [nshards]),
            "ts": time.time(),
            "shards": {i: shards[i] for i in range(nshards)},
        }
        # REGISTER FIRST: the new manifest must be discoverable (and
        # its blocks pinned here) before any older step is let go.
        if self._client is not None:
            self._client.kv_put(KV_CKPT_NS, self.run.encode(),
                                pickle.dumps(manifest))
        self._committed.append((step, shards, nshards))
        self._committed.sort(key=lambda c: c[0])
        self.log.append(("register", step))
        self.commits += 1
        # ONLY NOW release anything older than the retention window,
        # plus stale pending slots a resize orphaned mid-step.
        while len(self._committed) > self.keep:
            old_step, old_shards, old_n = self._committed.pop(0)
            for idx in list(old_shards):
                leaksan.discharge(
                    "ckpt_shard", (self.run, old_step, old_n, idx))
                del old_shards[idx]
            self.log.append(("release", old_step))
            self.releases += 1
        for key in [k for k in self._pending if k[0] <= step]:
            pstep, pn = key
            slot = self._pending.pop(key)
            for idx in list(slot):
                leaksan.discharge("ckpt_shard",
                                  (self.run, pstep, pn, idx))
                del slot[idx]

    def _manifest_dict(self, step: int, shards: Dict[int, Any],
                       nshards: int) -> Dict[str, Any]:
        return {
            "run": self.run,
            "step": step,
            "world_size": nshards,
            "mesh_shape": [nshards],
            "ts": time.time(),
            # Copy: retention mutates the committed dict in place.
            "shards": dict(shards),
        }

    def freeze(self, epoch: int) -> Optional[Dict[str, Any]]:
        """Pin epoch ``epoch``'s restore point: the first call for a
        new epoch snapshots the latest committed manifest, discards
        every partial pending slot (their writers' epoch is dead),
        and rejects publishes tagged with an older epoch from now on.
        Every member restoring for the same epoch gets the SAME
        manifest — which is what keeps the resharded gang in lockstep.
        Returns the manifest, or None when nothing has committed."""
        epoch = int(epoch)
        if epoch in self._frozen:
            return self._frozen[epoch]
        if epoch < self._min_epoch:
            # Laggard asking about a superseded epoch: hand back the
            # current restore point without disturbing the freeze.
            if self._committed:
                step, shards, nshards = self._committed[-1]
                return self._manifest_dict(step, shards, nshards)
            return None
        self._min_epoch = epoch
        for (pstep, pn), slot in list(self._pending.items()):
            for idx in list(slot):
                leaksan.discharge("ckpt_shard",
                                  (self.run, pstep, pn, idx))
                del slot[idx]
            self._pending.pop((pstep, pn), None)
        man = None
        if self._committed:
            step, shards, nshards = self._committed[-1]
            man = self._manifest_dict(step, shards, nshards)
        self._frozen = {epoch: man}
        return man

    # -- queries ---------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._committed[-1][0] if self._committed else None

    def stats(self) -> Dict[str, Any]:
        return {
            "run": self.run,
            "latest_step": self.latest_step(),
            "committed_steps": [c[0] for c in self._committed],
            "pending_slots": {f"{s}/{n}": len(v) for (s, n), v
                              in self._pending.items()},
            "refs_live": (sum(len(c[1]) for c in self._committed)
                          + sum(len(v)
                                for v in self._pending.values())),
            "commits": self.commits,
            "releases": self.releases,
            "log": list(self.log),
        }

    def release_all(self) -> int:
        """Drop every held ref (teardown).  The KV manifest entry is
        removed too — a manifest whose blocks are gone is a trap, not
        a checkpoint."""
        n = 0
        for step, shards, nshards in self._committed:
            for idx in list(shards):
                leaksan.discharge("ckpt_shard",
                                  (self.run, step, nshards, idx))
                del shards[idx]
                n += 1
        self._committed = []
        for (pstep, pn), slot in list(self._pending.items()):
            for idx in list(slot):
                leaksan.discharge("ckpt_shard",
                                  (self.run, pstep, pn, idx))
                del slot[idx]
                n += 1
        self._pending = {}
        if self._client is not None:
            try:
                self._client.kv_del(KV_CKPT_NS, self.run.encode())
            except Exception:
                pass
        return n


@ray_tpu.remote
class _CheckpointKeeper:
    """The per-run live owner of the in-cluster checkpoint: a named
    actor holding every committed shard ref (pinning the object-store
    blocks) and writing the step manifest to the KV.  One per run,
    spawned by the elastic coordinator; ``stop()`` releases the refs
    and discharges the leak ledger BEFORE the driver kills it (a
    SIGKILLed process dumps no ledger)."""

    def __init__(self, run: str, keep: int = 0) -> None:
        from ray_tpu._private.client import get_global_client
        self._store = ManifestStore(
            run, client=get_global_client(),
            keep=keep or None)

    def publish(self, step: int, index: int, nshards: int,
                ref_list: List[Any],
                meta: Optional[Dict[str, Any]] = None,
                epoch: int = 0) -> Optional[int]:
        # The shard ref travels INSIDE a list so it arrives as a ref
        # (a bare ObjectRef argument is materialized at the callee);
        # holding it in the store is what pins the block.
        return self._store.publish(step, index, nshards, ref_list[0],
                                   meta, epoch=epoch)

    def manifest_for_epoch(self, epoch: int
                           ) -> Optional[Dict[str, Any]]:
        # The returned manifest carries the shard ObjectRefs; the
        # caller borrows them on deserialize while this actor keeps
        # the blocks pinned.
        return self._store.freeze(epoch)

    def latest_step(self) -> Optional[int]:
        return self._store.latest_step()

    def stats(self) -> Dict[str, Any]:
        return self._store.stats()

    def stop(self) -> int:
        return self._store.release_all()


# ---------------------------------------------------------------------------
# gang record (driver writes, workers poll)
# ---------------------------------------------------------------------------
def read_gang(client, run: str) -> Optional[Dict[str, Any]]:
    try:
        blob = client.kv_get(KV_GANG_NS, run.encode())
    except Exception:
        return None
    if not blob:
        return None
    try:
        return json.loads(blob)
    except ValueError:
        return None


def write_gang(client, run: str, epoch: int, members: List[int],
               restore_step: Optional[int],
               notices: Optional[Dict[str, float]] = None) -> None:
    client.kv_put(KV_GANG_NS, run.encode(), json.dumps({
        "epoch": int(epoch),
        "members": sorted(int(m) for m in members),
        "world_size": len(members),
        "restore_step": restore_step,
        "notices": notices or {},
        "updated_ts": time.time(),
    }).encode())


def latest_manifest_step(client, run: str) -> Optional[int]:
    """The latest committed in-cluster checkpoint step (driver-side
    peek; the full manifest stays pickled for the workers)."""
    try:
        blob = client.kv_get(KV_CKPT_NS, run.encode())
        return int(pickle.loads(blob)["step"]) if blob else None
    except Exception:
        return None


def cleanup_run(client, run: str) -> None:
    """Delete a run's gang record and reduce slots (fit start/end).
    The manifest entry is the keeper's to remove (release_all) — it
    must not outlive the pinned blocks, nor be deleted while a reader
    may still resolve it."""
    try:
        client.kv_del(KV_GANG_NS, run.encode())
        for key in client.kv_keys(KV_REDUCE_NS,
                                  prefix=f"{run}{_SEP}".encode()):
            client.kv_del(KV_REDUCE_NS, key)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# worker-side session
# ---------------------------------------------------------------------------
class ElasticSession:
    """A train worker's handle on the elastic plane: gang membership,
    sharded checkpoint save/restore, and a resize-aware allreduce.

    Typical loop (see tests/test_train_elastic.py)::

        es = session.get_context().elastic()
        es.join()
        t, state = 0, init_state()
        got = es.restore()
        if got:
            t, state = got[0] + 1, got[1]
        while t < total_steps:
            ev = es.sync()
            if ev and ev["resized"]:
                with tel.resize():
                    t, state = es.restore_or(t, state)
                continue
            if ev and ev["notice_deadline"]:
                es.save_shard(t - 1, state, force=True)
                return                      # graceful preempt exit
            grad = ...                      # this member's shard of work
            grad = es.allreduce(t, grad, weight=my_batch_len)
            state = apply(state, grad)
            es.save_shard(t, state)
            t += 1
    """

    def __init__(self, run: str, rank: int, client: Any = None,
                 telemetry_provider: Optional[Callable[[], Any]] = None
                 ) -> None:
        if client is None:
            from ray_tpu._private.client import get_global_client
            client = get_global_client()
        self._client = client
        self._run = run
        self._rank = int(rank)
        self._tel = telemetry_provider or (lambda: None)
        self._keeper = None
        self._epoch = -1
        self._members: List[int] = []
        self._last_save = 0.0
        self._last_sync = 0.0
        # Pin the last few put refs: the keeper's borrow lands only
        # when it DESERIALIZES the publish args, and the publisher
        # dropping its owned ref first would strand the handoff (the
        # PR-4 last-borrow trap, on the write side).
        self._recent_refs: deque = deque(maxlen=4)

    # -- membership ------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def members(self) -> List[int]:
        return list(self._members)

    def shard_index(self) -> int:
        return self._members.index(self._rank)

    def _keeper_handle(self):
        if self._keeper is None:
            self._keeper = ray_tpu.get_actor(keeper_name(self._run))
        return self._keeper

    def join(self, timeout: float = 30.0) -> Dict[str, Any]:
        """Block until the gang record exists; adopt its epoch."""
        deadline = time.monotonic() + timeout
        while True:
            g = read_gang(self._client, self._run)
            if g is not None:
                self._epoch = int(g["epoch"])
                self._members = [int(m) for m in g["members"]]
                return g
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no gang record for run {self._run!r}")
            time.sleep(0.02)

    def sync(self, force: bool = True) -> Optional[Dict[str, Any]]:
        """Poll the gang record.  Returns None when rate-limited
        (force=False) or the record is missing; otherwise a dict with
        ``resized`` (the epoch moved — reshard before continuing),
        the new ``epoch``/``members``/``restore_step``, and this
        rank's ``notice_deadline`` (a preemption notice: save a final
        shard and exit gracefully)."""
        now = time.monotonic()
        if not force and now - self._last_sync < float(
                config.train_elastic_poll_s):
            return None
        self._last_sync = now
        g = read_gang(self._client, self._run)
        if g is None:
            return None
        resized = int(g["epoch"]) != self._epoch
        if resized:
            self._epoch = int(g["epoch"])
            self._members = [int(m) for m in g["members"]]
        notice = (g.get("notices") or {}).get(str(self._rank))
        return {"resized": resized, "epoch": self._epoch,
                "members": list(self._members),
                "restore_step": g.get("restore_step"),
                "notice_deadline": notice}

    # -- sharded checkpoint ---------------------------------------------
    def save_shard(self, step: int, state: Any,
                   force: bool = False) -> bool:
        """Snapshot this member's shard of ``state`` into the object
        store and hand the ref to the keeper.  Cadence-gated by
        ``train_ckpt_interval_s`` unless forced (0 = every call).
        Returns True when a shard was published."""
        interval = float(config.train_ckpt_interval_s)
        now = time.monotonic()
        if not force and interval > 0 and (
                now - self._last_save < interval):
            return False
        if self._rank not in self._members:
            return False
        idx = self.shard_index()
        n = len(self._members)
        tel = self._tel()
        timer = tel.checkpoint() if tel is not None else None
        if timer is not None:
            timer.__enter__()
        try:
            ref = ray_tpu.put(shard_pytree(state, idx, n))
            self._recent_refs.append(ref)
            # Fire-and-forget: the snapshot is asynchronous by design;
            # commit consistency is the keeper's job.  The epoch tag
            # lets the keeper drop publishes that raced a resize.
            self._keeper_handle().publish.remote(  # ray-tpu: noqa[RT006]
                int(step), idx, n, [ref], {"mesh_shape": [n]},
                self._epoch)
        finally:
            if timer is not None:
                timer.__exit__(None, None, None)
        self._last_save = now
        return True

    def restore(self) -> Optional[Tuple[int, Any]]:
        """Pull this epoch's consistent in-cluster checkpoint from the
        keeper and reassemble the FULL state: (step, state), or None
        when no manifest has been committed yet.  The keeper freezes
        the epoch's restore point on first ask, so every member of
        the epoch restores the SAME step.  Counts as a 'memory'
        checkpoint read — never touches disk."""
        try:
            man = ray_tpu.get(
                self._keeper_handle().manifest_for_epoch.remote(
                    self._epoch), timeout=60)
        except Exception:
            return None
        if man is None:
            return None
        refs = [man["shards"][i] for i in range(int(man["world_size"]))]
        shards = [ray_tpu.get(r) for r in refs]
        state = unshard_pytree(shards)
        tel = self._tel()
        if tel is not None:
            tel.note_ckpt_read("memory")
        return int(man["step"]), state

    def restore_or(self, step: int, state: Any
                   ) -> Tuple[int, Any]:
        """restore(), falling back to the caller's current (step,
        state) when no manifest exists yet (resize before the first
        commit).  Returns the NEXT step to run."""
        got = self.restore()
        if got is None:
            return step, state
        return got[0] + 1, got[1]

    # -- resize-aware collective ----------------------------------------
    def allreduce(self, step: int, tree: Any,
                  weight: float = 1.0,
                  timeout: float = 60.0) -> Any:
        """Weighted-mean allreduce over the CURRENT members through
        the control-plane KV: post (weight, tree), wait for every
        member's contribution for (epoch, step), return
        sum(w_i * tree_i) / sum(w_i).

        With weight = this member's shard size, the weighted mean of
        per-shard gradients IS the full-batch gradient at any world
        size — the loss-curve-equivalence invariant.  Raises
        :class:`ResizeInterrupt` when the epoch moves mid-wait (a
        member died): the caller reshards and replays the step."""
        epoch = self._epoch
        mine = self._reduce_key(epoch, step, self._rank)
        self._client.kv_put(KV_REDUCE_NS, mine,
                            pickle.dumps((float(weight), tree)))
        members = list(self._members)
        poll = min(float(config.train_elastic_poll_s), 0.02)
        deadline = time.monotonic() + timeout
        got: Dict[int, Any] = {}
        while True:
            for m in members:
                if m in got:
                    continue
                blob = self._client.kv_get(
                    KV_REDUCE_NS, self._reduce_key(epoch, step, m))
                if blob:
                    got[m] = pickle.loads(blob)
            if len(got) == len(members):
                break
            g = read_gang(self._client, self._run)
            if g is not None and int(g["epoch"]) != epoch:
                raise ResizeInterrupt(
                    f"epoch {epoch} -> {g['epoch']} during allreduce "
                    f"at step {step}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"allreduce step {step}: "
                    f"{sorted(set(members) - set(got))} missing")
            time.sleep(poll)
        total_w = sum(w for w, _ in got.values())
        acc = None
        for m in members:
            w, t = got[m]
            acc = _tree_scale_add(acc, t, w)
        # Everyone posted (epoch, step), so everyone has FINISHED
        # reading (epoch, step-1) — this rank's previous slot can go.
        if step > 0:
            try:
                self._client.kv_del(
                    KV_REDUCE_NS,
                    self._reduce_key(epoch, step - 1, self._rank))
            except Exception:
                pass
        return _tree_scale(acc, 1.0 / max(total_w, 1e-12))

    def _reduce_key(self, epoch: int, step: int, rank: int) -> bytes:
        return (f"{self._run}{_SEP}{epoch}{_SEP}{step}"
                f"{_SEP}{rank}").encode()


# ---------------------------------------------------------------------------
# driver-side coordinator
# ---------------------------------------------------------------------------
def run_elastic_attempt(trainer, trial_dir: str, manager, restore,
                        attempt: int, history: List[Dict[str, Any]],
                        actor_opts: Dict[str, Any],
                        report_ns: str) -> Dict[str, Any]:
    """The elastic replacement for TpuTrainer._run_attempt: spawn the
    keeper + gang, then drive the wait/drain loop with shrink-on-
    preempt and grow-back instead of fail-the-attempt.  Falls through
    to the caller's restart path (by re-raising the worker death) only
    when a shrink would cross ``train_min_world_size``."""
    import os

    from ray_tpu import exceptions as exc
    from ray_tpu._private.chaos import chaos
    from ray_tpu.train import telemetry as telemetry_mod
    from ray_tpu.train.trainer import _TrainWorker

    client = ray_tpu._ensure_connected()
    run_name = os.path.basename(trial_dir.rstrip("/"))
    world0 = trainer._scaling.num_workers
    min_world = max(int(config.train_min_world_size), 1)
    poll_s = max(float(config.train_elastic_poll_s), 0.05)
    grow_retry_s = max(float(config.train_grow_retry_s), 0.1)

    cleanup_run(client, run_name)
    keeper = _CheckpointKeeper.options(
        name=keeper_name(run_name)).remote(run_name)
    # The keeper must be resolvable by name before any worker's first
    # save_shard; ping synchronously.
    ray_tpu.get(keeper.latest_step.remote(), timeout=60)

    epoch = 0
    members = list(range(world0))
    notices: Dict[str, float] = {}
    write_gang(client, run_name, epoch, members, None, notices)
    telemetry_mod.set_world_size_gauge(run_name, len(members))

    def _spawn(rank: int, recovery_class: str):
        cls = (_TrainWorker.options(**actor_opts) if actor_opts
               else _TrainWorker)
        w = cls.remote(rank, world0, trial_dir,
                       trainer._config or {}, restore, report_ns,
                       None, recovery_class)
        return w, w.run.remote((trainer._fn, trainer._config))

    workers: Dict[int, Any] = {}
    pending: Dict[Any, int] = {}         # run ref -> rank
    for rank in members:
        w, ref = _spawn(rank, "restart_recovery")
        workers[rank] = w
        pending[ref] = rank

    straggler_check_s = float(config.train_straggler_check_s)
    next_straggler = time.time() + straggler_check_s
    kill_at: Dict[int, float] = {}       # noticed rank -> hard deadline
    next_grow = 0.0
    last_resize_start = 0.0
    done_ranks: set = set()

    def _shrink(victim: int) -> None:
        nonlocal epoch, last_resize_start, next_grow
        t0 = time.time()
        members.remove(victim)
        notices.pop(str(victim), None)
        kill_at.pop(victim, None)
        w = workers.pop(victim, None)
        if w is not None:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        epoch += 1
        step = latest_manifest_step(client, run_name)
        write_gang(client, run_name, epoch, members, step, notices)
        telemetry_mod.record_resize(
            client, run_name, "shrink", len(members) + 1,
            len(members), step if step is not None else -1,
            dead_s=time.time() - t0)
        last_resize_start = time.monotonic()
        next_grow = time.monotonic() + grow_retry_s

    def _grow() -> None:
        nonlocal epoch, next_grow
        missing = sorted(set(range(world0)) - set(members)
                         - done_ranks)
        if not missing:
            return
        # A replacement can only join in lockstep by resharding from a
        # committed manifest; until one exists it would start at step
        # 0 while survivors are ahead, and the gang would never agree
        # on a step again.  Re-probe on the grow cadence.
        if latest_manifest_step(client, run_name) is None:
            next_grow = time.monotonic() + grow_retry_s
            return
        rank = missing[0]
        t0 = time.time()
        # The replacement's telemetry session charges its restore gap
        # to resize_recovery, not restart_recovery.
        w, ref = _spawn(rank, "resize_recovery")
        workers[rank] = w
        pending[ref] = rank
        members.append(rank)
        members.sort()
        epoch += 1
        step = latest_manifest_step(client, run_name)
        write_gang(client, run_name, epoch, members, step, notices)
        telemetry_mod.record_resize(
            client, run_name, "grow", len(members) - 1,
            len(members), step if step is not None else -1,
            dead_s=time.time() - t0)
        next_grow = time.monotonic() + grow_retry_s

    try:
        while pending:
            ready, _ = ray_tpu.wait(
                list(pending), num_returns=len(pending),
                timeout=min(poll_s, 0.25))
            trainer._drain(report_ns, manager, history)
            if (straggler_check_s > 0
                    and time.time() >= next_straggler):
                next_straggler = time.time() + straggler_check_s
                trainer._check_stragglers(run_name)

            # Preemption storm: the chaos schedule delivers a notice
            # (deadline_s of grace, then a hard kill) to the HIGHEST
            # active rank — deterministic victim choice keeps the
            # seeded trace a replay witness.
            spec = chaos.fire_spec("train.worker", "preempt")
            if spec is not None and members:
                victim = max(members)
                if len(members) - 1 >= min_world:
                    grace = float(spec.get("deadline_s") or 0.0)
                    if grace > 0:
                        notices[str(victim)] = time.time() + grace
                        kill_at[victim] = time.monotonic() + grace
                        write_gang(client, run_name, epoch, members,
                                   None, notices)
                    else:
                        kill_at[victim] = time.monotonic()

            # Hard-kill noticed workers whose grace expired.
            for rank, due in list(kill_at.items()):
                if time.monotonic() >= due:
                    kill_at.pop(rank, None)
                    w = workers.get(rank)
                    if w is not None:
                        try:
                            ray_tpu.kill(w)
                        except Exception:
                            pass

            for r in ready:
                rank = pending.pop(r)
                try:
                    tb = ray_tpu.get(r)
                except (exc.ActorDiedError,
                        exc.WorkerCrashedError,
                        exc.ActorUnavailableError) as death:
                    if (rank in members
                            and len(members) - 1 >= min_world):
                        _shrink(rank)
                        continue
                    raise death
                if tb is not None:
                    raise exc.TaskError("train_loop_per_worker", tb)
                if str(rank) in notices or rank in kill_at:
                    # Graceful preempt exit: the worker saved a final
                    # shard and returned — a shrink, not a completion.
                    _shrink(rank)
                else:
                    done_ranks.add(rank)

            # Grow-back: capacity "heals" when the scheduler can place
            # a replacement; probe on a cadence after the last resize.
            if (set(range(world0)) - set(members) - done_ranks
                    and time.monotonic() >= next_grow
                    and next_grow > 0):
                _grow()

        trainer._drain(report_ns, manager, history)
        return history[-1] if history else {}
    except (exc.ActorDiedError, exc.WorkerCrashedError):
        trainer._drain(report_ns, manager, history)
        raise
    finally:
        for w in workers.values():
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        # Release the pinned shard blocks BEFORE killing the keeper:
        # a SIGKILLed keeper dumps no leak ledger and strands its
        # borrows until GC notices the dead process.
        try:
            ray_tpu.get(keeper.stop.remote(), timeout=30)
        except Exception:
            pass
        try:
            ray_tpu.kill(keeper)
        except Exception:
            pass
        cleanup_run(client, run_name)
