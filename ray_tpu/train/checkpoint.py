"""Checkpointing: directory-handle checkpoints + top-K retention manager.

Analog of the reference's ray.train.Checkpoint (train/_checkpoint.py:56 —
a handle to a directory on pluggable storage) and CheckpointManager
(train/_internal/checkpoint_manager.py — keep top-K by metric).  The TPU
difference: sharded jax pytrees are saved/restored via orbax, which
writes per-shard tensorstore files in parallel across hosts — the
TPU-native equivalent of torch.distributed checkpoint.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A handle to a checkpoint directory (local or fsspec-style path)."""

    def __init__(self, path: str) -> None:
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    # -- pytree (jax) payloads ------------------------------------------
    @classmethod
    def save_pytree(cls, path: str, tree: Any,
                    metadata: Optional[Dict[str, Any]] = None
                    ) -> "Checkpoint":
        """Save a (possibly sharded) jax pytree with orbax."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "state"), tree, force=True)
        ckptr.wait_until_finished()
        if metadata:
            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(metadata, f)
        return cls(path)

    def load_pytree(self, abstract_tree: Any = None) -> Any:
        """Restore; `abstract_tree` (jax.eval_shape output with shardings)
        restores shards to the right devices."""
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(os.path.join(self.path, "state"),
                             abstract_tree)

    def metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "metadata.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"


@dataclass
class _Tracked:
    checkpoint: Checkpoint
    metrics: Dict[str, Any]
    index: int


class CheckpointManager:
    """Keep the best K checkpoints by a metric (reference:
    CheckpointConfig(num_to_keep, checkpoint_score_attribute, ...))."""

    def __init__(self, directory: str, num_to_keep: Optional[int] = 2,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max") -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._tracked: List[_Tracked] = []
        self._counter = 0

    def next_checkpoint_path(self) -> str:
        return os.path.join(self.directory,
                            f"checkpoint_{self._counter:06d}")

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> None:
        # Re-reporting the same directory updates the entry in place —
        # never track one path twice, or eviction would rmtree data the
        # latest checkpoint still points to.
        for t in self._tracked:
            if t.checkpoint.path == checkpoint.path:
                t.metrics = metrics or {}
                t.index = self._counter
                self._counter += 1
                return
        self._tracked.append(
            _Tracked(checkpoint, metrics or {}, self._counter))
        self._counter += 1
        self._evict()

    def _score(self, t: _Tracked) -> float:
        if self.score_attribute is None:
            return float(t.index)  # recency
        v = float(t.metrics.get(self.score_attribute, float("-inf")))
        return v if self.score_order == "max" else -v

    def _evict(self) -> None:
        if self.num_to_keep is None:
            return
        while len(self._tracked) > self.num_to_keep:
            worst = min(self._tracked, key=self._score)
            self._tracked.remove(worst)
            shutil.rmtree(worst.checkpoint.path, ignore_errors=True)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=self._score).checkpoint

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=lambda t: t.index).checkpoint

    def list_checkpoints(self) -> List[Checkpoint]:
        return [t.checkpoint for t in
                sorted(self._tracked, key=lambda t: t.index)]
