"""ray_tpu.train: distributed training orchestration (reference: Ray Train).

Public surface mirrors ray.train: TpuTrainer (DataParallelTrainer
analog), ScalingConfig/RunConfig/FailureConfig/CheckpointConfig, Result,
Checkpoint, session get_context()/report(); plus the TPU-native
compile-once sharded step (CompiledTrainStep) replacing torch DDP
backends.  The jax/optax-heavy train_step symbols are lazy (PEP 562) so
CPU-only trainer workers don't pay the jax import.
"""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.session import (get_context,
                                   get_dataset_shard, report)
from ray_tpu.train.trainer import (CheckpointConfig, DataParallelTrainer,
                                   FailureConfig, Result, RunConfig,
                                   ScalingConfig, TpuTrainer)

_LAZY = {"CompiledTrainStep", "TrainState", "make_optimizer"}


def __getattr__(name):
    if name in _LAZY:
        from ray_tpu.train import train_step
        return getattr(train_step, name)
    raise AttributeError(name)


__all__ = [
    "Checkpoint", "CheckpointManager", "get_context", "get_dataset_shard", "report",
    "CheckpointConfig", "DataParallelTrainer", "FailureConfig", "Result",
    "RunConfig", "ScalingConfig", "TpuTrainer", "CompiledTrainStep",
    "TrainState", "make_optimizer",
]
