"""ray_tpu.train: distributed training orchestration (reference: Ray Train).

Public surface mirrors ray.train: TpuTrainer (DataParallelTrainer
analog), ScalingConfig/RunConfig/FailureConfig/CheckpointConfig, Result,
Checkpoint, session get_context()/report(); plus the TPU-native
compile-once sharded step (CompiledTrainStep) replacing torch DDP
backends.  The jax/optax-heavy train_step symbols are lazy (PEP 562) so
CPU-only trainer workers don't pay the jax import.

Telemetry (train/telemetry.py): every worker can open a
``TrainTelemetry`` session — ``session.get_context().telemetry(...)``
inside a train loop, or ``TrainTelemetry(run, client=None)`` offline —
that decomposes each step's wall clock into data_wait / compile /
step / checkpoint / sync (+ implicit idle), keeps a live
decayed-window tokens/s + MFU readout, maintains a run-level goodput
ledger (productive / compile / input_wait / checkpoint / sync /
restart_recovery / idle) that survives worker restarts through the
control-plane KV, and publishes a rolling step window the trainer's
straggler reducer compares across the gang.  Every ``report()`` is
stamped with a monotonic ``_step`` index + ``_ts`` that survives
resume-from-checkpoint.  Read it back with
``state.train_summary()``, the dashboard ``/api/train`` endpoint, or
``ray_tpu train status [--json]``.

Elastic gang training (train/elastic.py): with
``ScalingConfig(elastic=True)`` (or ``train_elastic_enabled``) the
trainer resizes the gang in place on preemption — workers snapshot
sharded state into the object store on a cadence, a per-run keeper
actor registers consistent step manifests in the control-plane KV,
survivors reshard from the in-cluster checkpoint (zero disk reads)
at N−1, and the gang grows back when capacity heals.  Worker surface:
``session.get_context().elastic()`` -> ``ElasticSession``.
"""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.session import (get_context,
                                   get_dataset_shard, report)
from ray_tpu.train.trainer import (CheckpointConfig, DataParallelTrainer,
                                   FailureConfig, Result, RunConfig,
                                   ScalingConfig, TpuTrainer)

_LAZY = {"CompiledTrainStep", "TrainState", "make_optimizer"}


def __getattr__(name):
    if name in _LAZY:
        from ray_tpu.train import train_step
        return getattr(train_step, name)
    if name == "TrainTelemetry":
        from ray_tpu.train.telemetry import TrainTelemetry
        return TrainTelemetry
    if name in ("ElasticSession", "ResizeInterrupt"):
        from ray_tpu.train import elastic
        return getattr(elastic, name)
    raise AttributeError(name)


__all__ = [
    "Checkpoint", "CheckpointManager", "get_context", "get_dataset_shard", "report",
    "CheckpointConfig", "DataParallelTrainer", "FailureConfig", "Result",
    "RunConfig", "ScalingConfig", "TpuTrainer", "CompiledTrainStep",
    "TrainState", "TrainTelemetry", "make_optimizer",
    "ElasticSession", "ResizeInterrupt",
]
