"""Sharded training step: the compile-once pjit analog of the reference's
per-step Train loop.

Where the reference's TorchTrainer runs an eager torch loop with NCCL DDP
(train/torch/config.py:115 init_process_group) and stays out of the step
path (SURVEY.md §3.5), the TPU build compiles the ENTIRE step — forward,
backward, optimizer, metrics — into one XLA program over the mesh.  All
parallelism (dp / fsdp / tp / sp) is induced by the sharding rule table
(parallel/sharding.py); XLA inserts the psum/reduce-scatter/all-gather
collectives over ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import transformer
from ray_tpu.parallel.sharding import (DEFAULT_RULES, Rules, tree_specs,
                                       tree_shardings, use_mesh)


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(learning_rate: float = 3e-4, warmup_steps: int = 100,
                   total_steps: int = 10_000, weight_decay: float = 0.1,
                   b1: float = 0.9, b2: float = 0.95,
                   grad_clip: float = 1.0,
                   mu_dtype="bfloat16",
                   kind: str = "adamw") -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    if kind == "adafactor":
        # Factored second moment, no first moment: ~4 bytes/param of
        # optimizer state vs AdamW's 10 (f32 master + bf16 mu + f32 nu).
        # The T5/PaLM-lineage TPU optimizer — what lets a ~1.2B-param
        # model train on one 16 GB v5e chip, where AdamW's 12.4 GB of
        # state alone would blow HBM.  Adafactor does its own
        # update-magnitude clipping; no global-norm clip in the chain.
        # NOTE: no weight decay here.  optax.adafactor applies
        # `weight_decay_rate` per step WITHOUT lr-scaling (a flat
        # multiplicative shrink), so the AdamW-style 0.1 would shrink
        # every weight 10%/step and destroy training; the classic
        # T5-lineage Adafactor recipe runs without decoupled decay.
        return optax.adafactor(learning_rate=schedule)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        # bf16 first moment: halves mu's HBM traffic+footprint (~5% step
        # time on v5e, measured); the variance stays f32 — the standard
        # mixed-precision Adam recipe (e.g. T5X/MaxText defaults).
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


class CompiledTrainStep:
    """Holds the jitted step + sharded state constructors for one model."""

    def __init__(self, cfg: transformer.TransformerConfig, mesh,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 rules: Optional[Rules] = None,
                 donate_state: bool = True) -> None:
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules if rules is not None else DEFAULT_RULES
        self.optimizer = optimizer or make_optimizer()

        params_axes = transformer.logical_axes(cfg)
        self.param_shardings = tree_shardings(params_axes, mesh, self.rules)
        # Data: tokens [B, S+1] shard batch only — S+1 is odd-sized vs the
        # sp axis; activation constraints inside the model shard seq.
        from jax.sharding import NamedSharding
        from ray_tpu.parallel.sharding import spec_for
        self.data_sharding = NamedSharding(
            mesh, spec_for(("batch", None), self.rules, mesh))

        def init_fn(key):
            params = transformer.init_params(cfg, key)
            opt_state = self.optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32),
                              params=params, opt_state=opt_state)

        # Resolve opt-state shardings from its structure (eval_shape).
        key = jax.random.PRNGKey(0)
        state_shape = jax.eval_shape(init_fn, key)
        self.state_shardings = self._state_shardings(state_shape,
                                                    params_axes)
        self._init = jax.jit(init_fn,
                             out_shardings=self.state_shardings)

        def step_fn(state: TrainState, tokens) -> Tuple[TrainState, Dict]:
            with use_mesh(mesh):
                grad_fn = jax.value_and_grad(
                    lambda p: transformer.loss_fn(p, tokens, cfg, mesh),
                    has_aux=True)
                (loss, metrics), grads = grad_fn(state.params)
                updates, new_opt = self.optimizer.update(
                    grads, state.opt_state, state.params)
                new_params = optax.apply_updates(state.params, updates)
                metrics = dict(metrics)
                metrics["grad_norm"] = optax.global_norm(grads)
                return TrainState(state.step + 1, new_params,
                                  new_opt), metrics

        self._step = jax.jit(
            step_fn,
            in_shardings=(self.state_shardings, self.data_sharding),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,) if donate_state else ())

    def _state_shardings(self, state_shape, params_axes):
        from jax.sharding import NamedSharding, PartitionSpec

        replicated = NamedSharding(self.mesh, PartitionSpec())
        params_shardings = self.param_shardings
        params_treedef = jax.tree.structure(state_shape.params)
        params_leaves = jax.tree.leaves(state_shape.params)

        def mirrors_params(node) -> bool:
            # Adam mu/nu mirror the params pytree exactly; match by
            # structure + leaf shapes (NOT by flat shape — two equal-shaped
            # params with different rule shardings would alias, ADVICE r1).
            try:
                if jax.tree.structure(node) != params_treedef:
                    return False
                leaves = jax.tree.leaves(node)
                return all(getattr(a, "shape", None) == b.shape
                           for a, b in zip(leaves, params_leaves))
            except Exception:
                return False

        def assign(node):
            if mirrors_params(node):
                return params_shardings
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                return type(node)(*[assign(c) for c in node])
            if isinstance(node, (tuple, list)):
                return type(node)(assign(c) for c in node)
            if isinstance(node, dict):
                return {k: assign(v) for k, v in node.items()}
            return replicated  # scalar counts / schedule state

        return TrainState(
            step=replicated,
            params=params_shardings,
            opt_state=assign(state_shape.opt_state))

    # -- public API --------------------------------------------------------
    def init_state(self, seed: int = 0) -> TrainState:
        return self._init(jax.random.PRNGKey(seed))

    def _cache_size(self) -> int:
        """Compiled-variant count of the jitted step — telemetry's
        compile detector (train/telemetry.py device_step) watches
        this grow to classify a step as `compile` rather than `step`.
        Named like jax's own jit-cache accessor so CompiledTrainStep
        itself can be passed as a telemetry `jit_fns` entry."""
        try:
            return int(self._step._cache_size())
        except Exception:
            return -1

    def flops_per_token(self, seq: int,
                        n_params: Optional[int] = None) -> float:
        """Model FLOPs per trained token for this config (6N +
        attention; shared formula in train/telemetry.py)."""
        from ray_tpu.train.telemetry import transformer_flops_per_token
        if n_params is None:
            n_params = transformer.num_params(jax.eval_shape(
                lambda: transformer.init_params(
                    self.cfg, jax.random.PRNGKey(0))))
        return transformer_flops_per_token(
            n_params, self.cfg.n_layers, seq, self.cfg.d_model)

    def shard_batch(self, tokens) -> jax.Array:
        return jax.device_put(tokens, self.data_sharding)

    def __call__(self, state: TrainState, tokens
                 ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        return self._step(state, tokens)
