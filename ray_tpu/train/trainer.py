"""TpuTrainer: multi-worker training orchestration on the actor runtime.

Analog of the reference's DataParallelTrainer + BackendExecutor +
WorkerGroup stack (train/data_parallel_trainer.py:25,
train/_internal/backend_executor.py:68, _internal/worker_group.py:102):
N worker actors are gang-spawned with the requested resources, a
distributed context is established, the user's `train_loop_per_worker`
runs inside each worker, `session.report(...)` streams metrics and
checkpoint handles back to the driver, and FailureConfig governs
restart-from-last-checkpoint.

TPU-first differences:
  * A worker owns a whole HOST's chips (resources={"TPU": n}), not one
    GPU; in-worker parallelism is the jax mesh (train_step.py), so one
    worker per host is the norm and the "process group" is
    jax.distributed.initialize (coordinator = worker 0), not NCCL.
  * Checkpoints are orbax pytree saves (sharded, parallel across hosts).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train import session as session_mod


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0          # TPU chips reserved per worker
    resources_per_worker: Optional[Dict[str, float]] = None
    # Elastic gang training (train/elastic.py): shrink-in-place on
    # preemption, grow back when capacity heals, resharding from the
    # in-cluster checkpoint.  None defers to the
    # `train_elastic_enabled` config knob.
    elastic: Optional[bool] = None


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = 2
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    # Tune stop condition (reference: RunConfig stop): a dict
    # {metric: threshold} stopping a trial once result[metric] >=
    # threshold, or a callable (trial_id, result) -> bool.
    stop: Any = None


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[Exception]
    path: str
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None


@ray_tpu.remote
class _TrainWorker:
    """One training worker actor.  Reports write through to the control
    plane KV (session.py) so they survive worker crashes."""

    def __init__(self, rank: int, world_size: int, trial_dir: str,
                 config: Dict[str, Any],
                 restore_checkpoint: Optional[str],
                 report_ns: str,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 recovery_class: str = "restart_recovery") -> None:
        self._ctx = session_mod.TrainContext(
            world_size=world_size, world_rank=rank, trial_dir=trial_dir,
            restore_checkpoint=restore_checkpoint, config=config,
            report_ns=report_ns, dataset_shards=dataset_shards,
            recovery_class=recovery_class)
        session_mod.set_context(self._ctx)

    def run(self, fn_and_cfg) -> Optional[str]:
        fn, config = fn_and_cfg
        try:
            if config is not None:
                fn(config)
            else:
                fn()
            return None
        except BaseException as e:  # noqa: BLE001
            import traceback
            return "".join(traceback.format_exception(
                type(e), e, e.__traceback__))
        finally:
            # Clean-exit telemetry teardown: final snapshot publish,
            # publisher-thread join, per-run gauge removal.  A killed
            # worker skips this — the restarted session restores from
            # the last published snapshot and the driver force-zeroes
            # the gauges at fit() end.
            try:
                self._ctx._stop_telemetry()
            except Exception:
                pass


class TpuTrainer:
    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None) -> None:
        self._fn = train_loop_per_worker
        self._config = train_loop_config
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        # Named Datasets shard to workers via streaming_split; inside
        # the loop, session.get_dataset_shard(name) yields this rank's
        # DataIterator (reference: DataParallelTrainer datasets= +
        # ray.train.get_dataset_shard).
        self._datasets = datasets or {}
        self._stragglers_captured: set = set()

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        run_name = self._run_config.name or f"train_{int(time.time())}"
        storage = self._run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        trial_dir = os.path.join(storage, run_name)
        os.makedirs(trial_dir, exist_ok=True)
        ckpt_cfg = self._run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(trial_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order)

        failures_left = self._run_config.failure_config.max_failures
        restore: Optional[str] = None
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        error: Optional[Exception] = None
        self._stragglers_captured = set()
        # A fresh fit must not inherit a previous fit's telemetry
        # state under a reused run name (within-fit restarts DO
        # restore — workers only start publishing after this).
        from ray_tpu.train import telemetry as telemetry_mod
        try:
            telemetry_mod.reset_run(ray_tpu._ensure_connected(),
                                    run_name, trial_dir=trial_dir)
        except Exception:
            pass

        attempt = 0
        terminal = None          # None = aborted (non-retryable raise)
        try:
            while True:
                try:
                    last_metrics = self._run_attempt(
                        trial_dir, manager, restore, attempt, history)
                    error = None
                    terminal = "finished"
                    break
                except (exc.ActorDiedError, exc.WorkerCrashedError,
                        exc.TaskError) as e:
                    error = e
                    if failures_left == 0:
                        terminal = "failed"
                        break
                    failures_left -= 1
                    attempt += 1
                    latest = manager.latest_checkpoint
                    restore = latest.path if latest else None
        finally:
            # terminal stays None when the loop died on a
            # NON-retryable exception (KeyboardInterrupt, a control-
            # plane error out of _drain/wait): the run must not read
            # "finished" in `ray_tpu train status`.
            self._finalize_telemetry(run_name, terminal or "aborted")

        return Result(metrics=last_metrics,
                      checkpoint=manager.latest_checkpoint,
                      error=error, path=trial_dir,
                      metrics_dataframe=history)

    def _finalize_telemetry(self, run_name: str,
                            state: str) -> None:
        """Stamp the run's terminal state in the runs registry and
        force-zero its per-run gauges — workers that died uncleanly
        (SIGKILL mid-run) never ran their own remove(), and the
        node-side aggregate would hold their last samples forever
        (the PR-11 dead-writer gauge class)."""
        from ray_tpu.train import telemetry as telemetry_mod
        try:
            client = ray_tpu._ensure_connected()
            if telemetry_mod.read_snapshots(client, run_name):
                telemetry_mod.mark_run_state(client, run_name, state)
                # Only for runs that actually published telemetry:
                # force-zeroing unconditionally would MINT 9 node-side
                # series per fit (the aggregate never deletes series —
                # the very cardinality class RT015 exists to prevent).
                telemetry_mod.remove_run_gauges(run_name, force=True)
        except Exception:
            pass

    def _check_stragglers(self, run_name: str) -> None:
        """Driver-side straggler sweep over the workers' published
        step windows; each newly flagged rank gets ONE targeted stack
        capture through the stall-sentinel dump path.  The capture
        itself (a cluster stack_dump that can ride out a wedged
        node's 5s window) runs on a one-shot daemon thread so the
        drive loop keeps draining reports meanwhile."""
        import threading

        from ray_tpu.train import telemetry as telemetry_mod
        try:
            client = ray_tpu._ensure_connected()
            snaps = telemetry_mod.read_snapshots(client, run_name)
            if len(snaps) < 2:
                return
            for rank, verdict in telemetry_mod.straggler_verdicts(
                    snaps).items():
                if (verdict.get("straggler")
                        and rank not in self._stragglers_captured
                        and rank in snaps):
                    self._stragglers_captured.add(rank)
                    threading.Thread(
                        target=telemetry_mod.capture_straggler,
                        args=(client, run_name, rank, snaps[rank],
                              verdict),
                        daemon=True,
                        name=f"rtpu-straggler-capture-{rank}").start()
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _run_attempt(self, trial_dir: str, manager: CheckpointManager,
                     restore: Optional[str], attempt: int,
                     history: List[Dict[str, Any]]):
        s = self._scaling
        resources: Dict[str, float] = dict(s.resources_per_worker or {})
        actor_opts: Dict[str, Any] = {}
        if s.use_tpu:
            # use_tpu with unset chips means one chip per worker (the
            # reference's use_gpu=True -> 1 GPU convention); silently
            # training on CPU would be a trap.
            actor_opts["num_tpus"] = s.chips_per_worker or 1
        if resources:
            actor_opts["resources"] = resources
        report_ns = f"train_reports/{trial_dir}/{attempt}"

        from ray_tpu._private.config import config as _cfg
        elastic_enabled = (s.elastic if s.elastic is not None
                           else bool(_cfg.train_elastic_enabled))
        if elastic_enabled:
            if self._datasets:
                raise ValueError(
                    "elastic training does not support datasets= yet: "
                    "streaming splits are fixed-world (pass batches "
                    "through the loop config, or disable elastic)")
            from ray_tpu.train import elastic as elastic_mod
            return elastic_mod.run_elastic_attempt(
                self, trial_dir, manager, restore, attempt, history,
                actor_opts=actor_opts, report_ns=report_ns)

        # One streaming execution per named dataset, n per-rank feeds.
        # equal=True: SPMD training needs every rank to see the same
        # number of batches, or the stragglers hang in collectives —
        # work-stealing (equal=False) is for throughput consumers.
        shard_lists = {name: ds.streaming_split(s.num_workers,
                                                equal=True)
                       for name, ds in self._datasets.items()}
        coordinators = [its[0]._coord
                        for its in shard_lists.values() if its]
        workers = []
        for rank in range(s.num_workers):
            cls = (_TrainWorker.options(**actor_opts) if actor_opts
                   else _TrainWorker)
            shards = {name: its[rank]
                      for name, its in shard_lists.items()}
            w = cls.remote(rank, s.num_workers, trial_dir,
                           self._config or {}, restore, report_ns,
                           shards)
            workers.append(w)

        run_refs = [w.run.remote((self._fn, self._config))
                    for w in workers]
        run_name = os.path.basename(trial_dir.rstrip("/"))
        straggler_check_s = float(_cfg.train_straggler_check_s)
        next_straggler_check = time.time() + straggler_check_s
        try:
            pending = list(run_refs)
            while pending:
                ready, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0.25)
                self._drain(report_ns, manager, history)
                if (straggler_check_s > 0
                        and time.time() >= next_straggler_check):
                    next_straggler_check = (time.time()
                                            + straggler_check_s)
                    self._check_stragglers(run_name)
                for r in ready:
                    tb = ray_tpu.get(r)
                    if tb is not None:
                        raise exc.TaskError("train_loop_per_worker", tb)
            self._drain(report_ns, manager, history)
            return history[-1] if history else {}
        except (exc.ActorDiedError, exc.WorkerCrashedError):
            # Salvage reports (incl. checkpoints) written before death.
            self._drain(report_ns, manager, history)
            raise
        finally:
            # Coordinators too: each fit attempt spawns one per
            # dataset, and leaked ones pin their streaming execution's
            # block refs for the life of the cluster.
            for a in workers + coordinators:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    def _drain(self, report_ns: str, manager: CheckpointManager,
               history: List[Dict[str, Any]]) -> None:
        """Pull KV-buffered reports (rank 0's metrics are authoritative;
        any rank's checkpoints register)."""
        import pickle
        client = ray_tpu._ensure_connected()
        for key in sorted(client.kv_keys(report_ns)):
            blob = client.kv_get(report_ns, key)
            client.kv_del(report_ns, key)
            if blob is None:
                continue
            metrics, ckpt_path = pickle.loads(blob)
            rank = int(key.decode().split(":")[0])
            if rank == 0:
                history.append(metrics)
            if ckpt_path:
                manager.register(Checkpoint(ckpt_path), metrics)


# Reference-compatible alias: the DataParallelTrainer role.
DataParallelTrainer = TpuTrainer
