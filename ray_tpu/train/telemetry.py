"""Training telemetry & goodput plane: per-step decomposition, live
MFU, ingest-vs-compute attribution, straggler detection.

The train loop has been blind so far: MFU existed only as a post-hoc
average in bench.py, and nothing per-step reached the observability
plane.  This module is the instrument the ingest-disaggregation and
sharded-weight-update work (ROADMAP items 2/3) will be measured with:

* **Per-step decomposition** — each step's wall clock is split into
  ``data_wait`` (blocked on the next batch — the ingest-vs-compute
  signal), ``compile`` (tracing/lowering on jit-cache-miss steps),
  ``step`` (device compute), ``checkpoint``, ``sync``, and implicit
  ``idle`` (unattributed host time).  Phases are recorded with context
  managers (``tel.data_wait()``, ``tel.device_step()``, ...) and
  finalized by ``tel.end_step()``; compile is detected automatically
  when a registered jitted callable's cache grows across the
  ``device_step`` body.

* **Live MFU & goodput** — tokens/s over an exponentially decayed
  window (``train_mfu_halflife_s``), MFU from a declared
  ``flops_per_token`` (or estimated as 6·N from ``param_count``)
  against ``peak_flops``; plus a run-level *goodput ledger* that
  classifies every wall-clock second into productive / compile /
  input_wait / checkpoint / sync / restart_recovery / idle — so a
  chaos worker kill, a drain, or a GCS outage shows up as quantified
  lost goodput.  The ledger is persisted through the control-plane KV
  snapshot and restored on trainer restart: the gap between the dead
  worker's last snapshot and the restarted session's first breath is
  charged to ``restart_recovery``.

* **Cross-host step agreement** — every worker publishes its rolling
  step window; :func:`straggler_verdicts` flags a worker whose
  step-phase p95 exceeds the gang median by
  ``train_straggler_multiple``, and the trainer driver takes ONE
  targeted stack capture of the flagged worker through the PR-6
  stall-sentinel dump path.

Surfacing: ``state.train_summary()``, the dashboard ``/api/train``
endpoint, and ``ray_tpu train status [--json]``.  The metric names
live in util/metrics.py (``ray_tpu_train_step_seconds{phase}`` and
friends); per-run gauge series are removed on ``stop()`` (the RT015
contract) and registered with the leak ledger.

Offline mode: constructed with ``client=None`` (no runtime), the
session still decomposes steps, keeps the ledger, and records
process-local metrics — bench.py uses this for its steady-state MFU
capture.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from ray_tpu._private.config import config
from ray_tpu.devtools import leaksan
from ray_tpu.util import metrics as metrics_mod

# Explicit phases a step can attribute time to; anything left over in
# the step's wall clock lands in the implicit "idle" bucket.
PHASES = ("data_wait", "compile", "step", "checkpoint", "sync",
          "resize")

# Goodput ledger classes: every wall-clock second of the run lands in
# exactly one.  The five the goodput literature names (productive /
# compile / input_wait / restart_recovery / idle) plus checkpoint and
# sync split out so save/collective overhead is visible on its own,
# and resize_recovery so an elastic gang resize (reshard from the
# in-cluster checkpoint, train/elastic.py) is charged separately from
# a restart-from-disk.
LEDGER_CLASSES = ("productive", "compile", "input_wait", "checkpoint",
                  "sync", "restart_recovery", "resize_recovery",
                  "idle")

# The ledger classes a restart gap may be charged to (TrainTelemetry
# recovery_class=): the plain worker-restart path charges
# restart_recovery; an elastic replacement worker charges
# resize_recovery.
RECOVERY_CLASSES = ("restart_recovery", "resize_recovery")

_PHASE_TO_LEDGER = {"data_wait": "input_wait", "compile": "compile",
                    "step": "productive", "checkpoint": "checkpoint",
                    "sync": "sync", "resize": "resize_recovery"}

# Control-plane KV namespaces.  Snapshots are keyed
# "<run>\x1fw:<rank>" (worker snapshots) and "<run>\x1fs:<rank>"
# (straggler capture records); the runs registry maps run -> meta.
KV_RUNS_NS = "__train_runs__"
KV_SNAP_NS = "__train_telemetry__"
KV_SEQ_NS = "__train_report_seq__"
_SEP = "\x1f"

# bf16 peak per chip (moved here from bench.py so live MFU and the
# bench agree on the denominator).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e
    "cpu": 1e11,
}


def peak_flops_for(device) -> float:
    """Peak bf16 FLOPs/s for a jax device (CPU fallback 1e11)."""
    kind = getattr(device, "device_kind", "cpu")
    for name, peak in PEAK_FLOPS.items():
        if kind.startswith(name):
            return peak
    return PEAK_FLOPS["cpu"]


def transformer_flops_per_token(n_params: int, n_layers: int,
                                seq: int, d_model: int) -> float:
    """Model FLOPs per trained token: 6N + attention 12·L·s·d (PaLM
    appendix B) — the formula bench.py has always used, shared."""
    return 6.0 * n_params + 12.0 * n_layers * seq * d_model


def run_trace_id(run: str) -> str:
    """Deterministic 16-byte trace id shared by every span of a run —
    all workers and attempts compute the same id without a handshake
    (the lifecycle_span_id trick, applied per run)."""
    return hashlib.md5(run.encode()).hexdigest()


def _snap_key(run: str, rank: int) -> bytes:
    return f"{run}{_SEP}w:{rank:05d}".encode()


def _straggler_key(run: str, rank: int) -> bytes:
    return f"{run}{_SEP}s:{rank:05d}".encode()


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(len(sorted_vals) * q), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _median_low(sorted_vals: List[float]) -> float:
    """Lower-middle median: with an even count this picks the smaller
    middle element, so in a 2-worker gang the 'gang median' is the
    FAST worker's p95 and a slow peer can actually exceed
    multiple*median (the upper-middle convention made the slow
    worker its own yardstick — unflaggable by construction)."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[(len(sorted_vals) - 1) // 2]


class _PhaseTimer:
    """Context manager attributing its body's wall time to one phase."""

    __slots__ = ("_tel", "_phase", "_t0")

    def __init__(self, tel: "TrainTelemetry", phase: str) -> None:
        self._tel = tel
        self._phase = phase

    def __enter__(self) -> "_PhaseTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._tel._add_phase(self._phase,
                             time.perf_counter() - self._t0)


class _DeviceStepTimer:
    """Times the device-step body; classified ``compile`` when any
    registered jitted callable's cache grew across it (a shape-change
    step paid tracing/lowering), else ``step``."""

    __slots__ = ("_tel", "_tokens", "_t0", "_jit0")

    def __init__(self, tel: "TrainTelemetry",
                 tokens: Optional[int]) -> None:
        self._tel = tel
        self._tokens = tokens

    def __enter__(self) -> "_DeviceStepTimer":
        self._t0 = time.perf_counter()
        self._jit0 = self._tel._jit_cache_size()
        return self

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        jit1 = self._tel._jit_cache_size()
        compiled = self._jit0 >= 0 and jit1 > self._jit0
        self._tel._add_phase("compile" if compiled else "step", dt)
        self._tel._note_compile_sites()
        if self._tokens is not None:
            self._tel._note_tokens(self._tokens)


class TrainTelemetry:
    """One worker's telemetry session for one training run.

    Typical use inside a ``train_loop_per_worker`` (the trainer stops
    it automatically when the loop returns)::

        tel = session.get_context().telemetry(
            tokens_per_step=B * S, param_count=n_params,
            peak_flops=peak, jit_fns=[compiled_step])
        for batch in ...:
            with tel.data_wait():
                batch = next(it)
            with tel.device_step():
                state, m = compiled_step(state, batch)
            tel.end_step()

    Thread contract: the step API (phase timers, ``end_step``) is
    driven by the train loop thread; a small publisher thread pushes
    snapshots to the control-plane KV on ``train_telemetry_publish_s``
    so a wedged step still surfaces.  Shared state is guarded by
    ``self._lock``; KV/network pushes always run outside it.
    """

    def __init__(self, run: str, *, rank: int = 0, world_size: int = 1,
                 tokens_per_step: int = 0,
                 flops_per_token: Optional[float] = None,
                 param_count: Optional[int] = None,
                 peak_flops: Optional[float] = None,
                 jit_fns: Iterable[Any] = (),
                 client: Any = "auto",
                 publish: bool = True,
                 recovery_class: str = "restart_recovery") -> None:
        if recovery_class not in RECOVERY_CLASSES:
            raise ValueError(
                f"recovery_class {recovery_class!r} not in "
                f"{RECOVERY_CLASSES}")
        # Which ledger class the restore gap (last snapshot -> first
        # breath of this session) is charged to: restart_recovery for
        # the fixed-world restart path, resize_recovery for an elastic
        # replacement worker rejoining after a gang resize.
        self._recovery_class = recovery_class
        if client == "auto":
            from ray_tpu._private.client import get_global_client
            client = get_global_client()
        self._client = client
        self._run = run
        self._rank = int(rank)
        self._world_size = int(world_size)
        self._tokens_per_step = int(tokens_per_step or 0)
        if flops_per_token is None and param_count:
            # 6N: the dense-transformer floor (attention extra needs
            # layer shapes — pass flops_per_token for exactness).
            flops_per_token = 6.0 * float(param_count)
        self._flops_per_token = flops_per_token
        self._peak_flops = peak_flops
        self._jit_fns = [f for f in jit_fns
                         if hasattr(f, "_cache_size")]
        self._trace_id = run_trace_id(run)
        # This worker's node id (hex): disambiguates the straggler
        # stack capture's pid@node keys — bare pids collide across
        # hosts.
        self._node_id = ""
        if self._client is not None:
            try:
                nid = self._client.node_info().get("node_id")
                self._node_id = (nid.hex() if isinstance(nid, bytes)
                                 else str(nid or ""))
            except Exception:
                pass

        self._lock = threading.Lock()
        self._stopped = False
        self._phase_totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._ledger: Dict[str, float] = {c: 0.0
                                          for c in LEDGER_CLASSES}
        # Per-jit-site compile seconds (xlasan attribution): which
        # construction site the run's `compile` ledger class went to.
        self._compile_sites: Dict[str, float] = {}
        # Checkpoint-read accounting: how many restores this worker
        # served from the in-cluster object-store checkpoint vs from
        # disk — the elastic drill's zero-restart-from-disk witness.
        self._ckpt_reads: Dict[str, int] = {"memory": 0, "disk": 0}
        self._window: deque = deque(
            maxlen=max(int(config.train_telemetry_window), 8))
        self._step_index = 0
        self._restarts = 0
        self._t0 = time.time()           # run wall-clock origin
        self._cur: Dict[str, float] = {}
        self._cur_tokens: Optional[int] = None
        self._step_start = time.perf_counter()
        # Wall-clock frontier the ledger is complete up to (advanced
        # by end_step/stop).  Restart gaps are charged from HERE, not
        # from the last snapshot's push time — a snapshot pushed
        # mid-step would otherwise swallow the partial step's time.
        self._ledger_ts = time.time()
        # Decayed-window rate state (tokens/s, MFU).
        self._dec_tokens = 0.0
        self._dec_time = 0.0
        # Span batching (the PR-8 trap: never emit one driver event
        # per step on a fast loop).
        self._span_t0 = time.time()
        self._span_steps = 0
        self._span_phases: Dict[str, float] = {}
        self._last_publish = 0.0

        self._restore()

        # Per-phase pre-resolved observers: the step path skips the
        # tag merge/sort on every observation.
        hist = metrics_mod.shared_histogram(
            metrics_mod.TRAIN_STEP_SECONDS_METRIC,
            "Per-step training wall clock split by phase",
            boundaries=metrics_mod.TRAIN_STEP_BUCKETS,
            tag_keys=("phase",))
        self._hist_obs = {p: hist.observer(tags={"phase": p})
                          for p in PHASES + ("idle",)}
        self._mfu_gauge = metrics_mod.shared_gauge(
            metrics_mod.TRAIN_MFU_METRIC,
            "Live model-FLOPs utilization over a decayed window",
            tag_keys=("run",))
        self._tokens_gauge = metrics_mod.shared_gauge(
            metrics_mod.TRAIN_TOKENS_PER_S_METRIC,
            "Live training tokens/s over a decayed window",
            tag_keys=("run",))
        self._goodput_gauge = metrics_mod.shared_gauge(
            metrics_mod.TRAIN_GOODPUT_FRACTION_METRIC,
            "Run wall-clock ledger class as a fraction of wall",
            tag_keys=("run", "class"))

        # One switch for EVERYTHING that leaves the process (KV
        # snapshots, run meta, timeline spans, the publisher thread):
        # train_telemetry_enabled=False must take the telemetry plane
        # off the step path, not silently move its blocking kv_put
        # from the background thread onto the train loop.
        self._publish_enabled = (self._client is not None and publish
                                 and bool(
                                     config.train_telemetry_enabled))
        if self._publish_enabled and self._rank == 0:
            self._write_run_meta("running")

        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self._publish_enabled:
            t = threading.Thread(
                target=self._publish_loop, daemon=True,
                name=f"rtpu-train-telemetry-{run[:24]}")
            self._thread = t
            t.start()
            leaksan.track_thread(t, detail=f"train-telemetry {run}")

    # -- restore across restarts ----------------------------------------
    def _restore(self) -> None:
        """Resume cumulative state from the last published snapshot of
        this (run, rank): step index, phase totals, and the goodput
        ledger survive a worker kill; the dead time between the last
        snapshot and now is charged to restart_recovery."""
        if self._client is None:
            return
        try:
            blob = self._client.kv_get(KV_SNAP_NS,
                                       _snap_key(self._run, self._rank))
        except Exception:
            return
        if not blob:
            return
        try:
            snap = json.loads(blob)
        except ValueError:
            return
        for p, v in (snap.get("phases") or {}).items():
            if p in self._phase_totals:
                self._phase_totals[p] = float(v)
        for c, v in (snap.get("ledger") or {}).items():
            if c in self._ledger:
                self._ledger[c] = float(v)
        for s, v in (snap.get("compile_sites") or {}).items():
            self._compile_sites[s] = float(v)
        for src, v in (snap.get("ckpt_reads") or {}).items():
            if src in self._ckpt_reads:
                self._ckpt_reads[src] = int(v)
        self._step_index = int(snap.get("step_index") or 0)
        # An elastic replacement resuming after a gang resize is a
        # RESIZE, not a restart — it's already counted by
        # record_resize and must not inflate the restart column.
        self._restarts = (int(snap.get("restarts") or 0)
                          + (1 if self._recovery_class
                             == "restart_recovery" else 0))
        self._t0 = float(snap.get("t0") or self._t0)
        frontier = float(snap.get("ledger_ts") or snap.get("ts")
                         or time.time())
        gap = max(0.0, time.time() - frontier)
        self._ledger[self._recovery_class] += gap

    # -- step API --------------------------------------------------------
    def phase(self, name: str) -> _PhaseTimer:
        """Attribute the body's wall time to `name` (one of PHASES)."""
        if name not in PHASES:
            raise ValueError(f"unknown phase {name!r}; "
                             f"expected one of {PHASES}")
        return _PhaseTimer(self, name)

    def data_wait(self) -> _PhaseTimer:
        """Time blocked waiting on the next batch (the ingest signal)."""
        return _PhaseTimer(self, "data_wait")

    def checkpoint(self) -> _PhaseTimer:
        return _PhaseTimer(self, "checkpoint")

    def sync(self) -> _PhaseTimer:
        return _PhaseTimer(self, "sync")

    def resize(self) -> _PhaseTimer:
        """Time spent handling a gang resize (re-deriving the mesh,
        pulling and resharding the in-cluster checkpoint) — lands in
        the ledger's resize_recovery class."""
        return _PhaseTimer(self, "resize")

    def note_ckpt_read(self, source: str, n: int = 1) -> None:
        """Count a checkpoint restore by where the bytes came from:
        'memory' (in-cluster object-store shards) or 'disk'.  The
        elastic storm drill asserts disk stays at ZERO."""
        if source not in ("memory", "disk"):
            raise ValueError(
                f"ckpt read source {source!r} not in (memory, disk)")
        with self._lock:
            self._ckpt_reads[source] += int(n)

    def device_step(self, tokens: Optional[int] = None
                    ) -> _DeviceStepTimer:
        """Time the device compute; auto-classified as ``compile``
        when a registered jitted callable's cache grows across the
        body (jit cache miss = this step paid tracing/lowering).  The
        caller is responsible for making the body a real device fence
        (``block_until_ready`` / a host transfer on a scalar)."""
        return _DeviceStepTimer(self, tokens)

    def register_jit(self, fn: Any) -> None:
        """Add a jitted callable whose cache growth marks compile
        steps (e.g. ``CompiledTrainStep``'s jitted step)."""
        if hasattr(fn, "_cache_size"):
            with self._lock:
                self._jit_fns.append(fn)

    def end_step(self, tokens: Optional[int] = None) -> Dict[str, Any]:
        """Finalize the current step: record the wall split, update
        the rolling window, ledger, decayed rates, metrics, and the
        (rate-limited, batched) timeline span.  Returns the step
        record."""
        now_p = time.perf_counter()
        now_w = time.time()
        with self._lock:
            wall = max(0.0, now_p - self._step_start)
            phases = self._cur
            self._cur = {}
            attributed = sum(phases.values())
            idle = max(0.0, wall - attributed)
            if tokens is None:
                tokens = (self._cur_tokens
                          if self._cur_tokens is not None
                          else self._tokens_per_step)
            self._cur_tokens = None
            rec = {"i": self._step_index,
                   "ts": round(now_w, 3),
                   "wall": round(wall, 6),
                   "phases": {p: round(v, 6)
                              for p, v in phases.items()},
                   "tokens": int(tokens or 0)}
            self._window.append(rec)
            for p, v in phases.items():
                self._phase_totals[p] += v
                self._ledger[_PHASE_TO_LEDGER[p]] += v
            self._ledger["idle"] += idle
            self._ledger_ts = now_w
            self._step_index += 1
            self._step_start = now_p
            # Decayed-window rates: recent steps dominate, a pause
            # decays toward zero instead of averaging it away.
            halflife = max(float(config.train_mfu_halflife_s), 1e-3)
            decay = 0.5 ** (wall / halflife)
            self._dec_tokens = self._dec_tokens * decay + (tokens or 0)
            self._dec_time = self._dec_time * decay + wall
            tokens_rate = (self._dec_tokens / self._dec_time
                           if self._dec_time > 0 else 0.0)
            mfu = self._mfu_locked(tokens_rate)
            # Span batching state.
            self._span_steps += 1
            for p, v in phases.items():
                self._span_phases[p] = self._span_phases.get(p, 0) + v
            self._span_phases["idle"] = (
                self._span_phases.get("idle", 0.0) + idle)
            span_due = (self._publish_enabled
                        and now_w - self._span_t0
                        >= float(config.train_span_min_interval_s))
            if span_due:
                span = {"t0": self._span_t0, "t1": now_w,
                        "steps": self._span_steps,
                        "last_step": self._step_index - 1,
                        "phases": {p: round(v, 6) for p, v
                                   in self._span_phases.items()}}
                self._span_t0 = now_w
                self._span_steps = 0
                self._span_phases = {}
            else:
                span = None
            publish_due = (self._publish_enabled
                           and now_w - self._last_publish
                           >= float(
                               config.train_telemetry_publish_s))
            if publish_due:
                self._last_publish = now_w
                snap = self._snapshot_locked()
            else:
                snap = None
            gauges = self._rank == 0
            ledger_fracs = (self._ledger_fractions_locked()
                            if gauges else None)
        # Everything network/registry-flavored runs OUTSIDE the lock.
        for p, v in phases.items():
            self._hist_obs[p](v)
        if idle > 0:
            self._hist_obs["idle"](idle)
        if gauges:
            self._tokens_gauge.set(tokens_rate,
                                   tags={"run": self._run})
            if mfu is not None:
                self._mfu_gauge.set(mfu, tags={"run": self._run})
            for c, f in ledger_fracs.items():
                self._goodput_gauge.set(
                    f, tags={"run": self._run, "class": c})
        if span is not None:
            self._emit_span(span)
        if snap is not None:
            self._push_snapshot(snap)
        return rec

    def _add_phase(self, phase: str, dt: float) -> None:
        with self._lock:
            self._cur[phase] = self._cur.get(phase, 0.0) + dt

    def _note_compile_sites(self) -> None:
        """With the xlasan wrapper installed, drain its (site,
        seconds) compile events into this run's attribution map — the
        `compile` goodput class broken down by jit construction
        site."""
        try:
            from ray_tpu.devtools import xlasan
            if not xlasan.enabled():
                return
            events = xlasan.take_recent_compiles()
        except Exception:
            return
        if not events:
            return
        with self._lock:
            for site, secs in events:
                self._compile_sites[site] = (
                    self._compile_sites.get(site, 0.0) + secs)

    def _note_tokens(self, tokens: int) -> None:
        with self._lock:
            self._cur_tokens = (self._cur_tokens or 0) + int(tokens)

    def _jit_cache_size(self) -> int:
        fns = self._jit_fns
        if not fns:
            return -1
        try:
            return sum(int(f._cache_size()) for f in fns)
        except Exception:
            return -1

    def _mfu_locked(self, tokens_rate: float) -> Optional[float]:
        if not self._flops_per_token or not self._peak_flops:
            return None
        return tokens_rate * self._flops_per_token / self._peak_flops

    def _ledger_fractions_locked(self) -> Dict[str, float]:
        wall = max(time.time() - self._t0, 1e-9)
        return {c: min(v / wall, 1.0)
                for c, v in self._ledger.items()}

    # -- spans -----------------------------------------------------------
    def _emit_span(self, span: Dict[str, Any]) -> None:
        """One batched timeline span covering `steps` steps, on the
        run's shared trace id."""
        if not self._publish_enabled:
            return
        from ray_tpu._private import tracing
        try:
            self._client.profile_event({
                "name": f"train.step[{self._run}]",
                "start": span["t0"], "end": span["t1"],
                "pid": os.getpid(), "user": True,
                "trace_id": self._trace_id,
                "span_id": tracing.new_span_id(),
                "extra": {"run": self._run, "rank": self._rank,
                          "steps": span["steps"],
                          "last_step": span["last_step"],
                          "phases": span["phases"]},
            })
        except Exception:
            pass

    # -- snapshots / publish --------------------------------------------
    def _snapshot_locked(self) -> Dict[str, Any]:
        """Caller holds self._lock."""
        now = time.time()
        wall = max(now - self._t0, 0.0)
        tokens_rate = (self._dec_tokens / self._dec_time
                       if self._dec_time > 0 else 0.0)
        return {
            "run": self._run,
            "rank": self._rank,
            "world_size": self._world_size,
            "pid": os.getpid(),
            "node_id": self._node_id,
            "host": socket.gethostname(),
            "ts": now,
            "t0": self._t0,
            "ledger_ts": self._ledger_ts,
            "wall_s": wall,
            "restarts": self._restarts,
            "step_index": self._step_index,
            "phases": {p: round(v, 6)
                       for p, v in self._phase_totals.items()},
            "ledger": {c: round(v, 6)
                       for c, v in self._ledger.items()},
            "compile_sites": {s: round(v, 6)
                              for s, v in self._compile_sites.items()},
            "ckpt_reads": dict(self._ckpt_reads),
            "tokens_per_s": tokens_rate,
            "mfu": self._mfu_locked(tokens_rate),
            "flops_per_token": self._flops_per_token,
            "window": list(self._window),
            "stopped": self._stopped,
        }

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._snapshot_locked()

    def summary(self) -> Dict[str, Any]:
        """Local single-worker rollup (offline mode's face; the
        cluster face is state.train_summary())."""
        snap = self.snapshot()
        return summarize_run({"run": self._run,
                              "world_size": self._world_size,
                              "state": ("stopped" if snap["stopped"]
                                        else "running")},
                             {self._rank: snap})

    def _push_snapshot(self, snap: Dict[str, Any]) -> None:
        if not self._publish_enabled:
            return
        try:
            self._client.kv_put(KV_SNAP_NS,
                                _snap_key(self._run, self._rank),
                                json.dumps(snap).encode())
        except Exception:
            pass

    def _write_run_meta(self, state: str) -> None:
        # Read-modify-write: the elastic driver's record_resize shares
        # this key — a blind overwrite here would drop resize history
        # recorded before this session came up (a shrink can land
        # before rank 0's first breath).
        try:
            blob = self._client.kv_get(KV_RUNS_NS, self._run.encode())
            meta = json.loads(blob) if blob else {}
        except Exception:
            meta = {}
        meta["run"] = self._run
        meta["started_ts"] = self._t0
        meta["state"] = state
        # record_resize owns world_size once a resize happened.
        if "resizes" not in meta:
            meta["world_size"] = self._world_size
        try:
            self._client.kv_put(KV_RUNS_NS, self._run.encode(),
                                json.dumps(meta).encode())
        except Exception:
            pass

    def _publish_loop(self) -> None:
        interval = max(float(config.train_telemetry_publish_s), 0.05)
        while not self._stop_event.wait(interval):
            with self._lock:
                self._last_publish = time.time()
                snap = self._snapshot_locked()
            self._push_snapshot(snap)

    # -- teardown --------------------------------------------------------
    @property
    def step_index(self) -> int:
        with self._lock:
            return self._step_index

    def stop(self) -> None:
        """Finalize the session: fold the partial step into the
        ledger, stop and join the publisher, push the last snapshot,
        and remove this run's per-run gauge series (the RT015
        contract — repeated runs must not accumulate dead cells)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            # The partial step's attributed phases count; the tail
            # since the last end_step is idle.
            tail = max(0.0, time.perf_counter() - self._step_start)
            for p, v in self._cur.items():
                self._phase_totals[p] += v
                self._ledger[_PHASE_TO_LEDGER[p]] += v
            self._ledger["idle"] += max(
                0.0, tail - sum(self._cur.values()))
            self._ledger_ts = time.time()
            self._cur = {}
            snap = self._snapshot_locked()
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            if not t.is_alive():
                leaksan.discharge_thread(t)
        self._push_snapshot(snap)
        if self._rank == 0:
            self._mfu_gauge.remove(tags={"run": self._run})
            self._tokens_gauge.remove(tags={"run": self._run})
            for c in LEDGER_CLASSES:
                self._goodput_gauge.remove(
                    tags={"run": self._run, "class": c})
        # Push pending metric deltas NOW: a short-lived train worker
        # is killed by the trainer right after its loop returns, and
        # the 1s daemon flusher would lose the final step histograms.
        try:
            metrics_mod.flush()
        except Exception:
            pass

    def __enter__(self) -> "TrainTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# cluster-side reducers (driver / state API)
# ---------------------------------------------------------------------------
def read_run_metas(client) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for key in client.kv_keys(KV_RUNS_NS):
        blob = client.kv_get(KV_RUNS_NS, key)
        if not blob:
            continue
        try:
            meta = json.loads(blob)
        except ValueError:
            continue
        out[key.decode()] = meta
    return out


def read_snapshots(client, run: str) -> Dict[int, Dict[str, Any]]:
    """{rank: latest snapshot} for one run."""
    out: Dict[int, Dict[str, Any]] = {}
    prefix = f"{run}{_SEP}w:".encode()
    for key in client.kv_keys(KV_SNAP_NS, prefix=prefix):
        blob = client.kv_get(KV_SNAP_NS, key)
        if not blob:
            continue
        try:
            snap = json.loads(blob)
        except ValueError:
            continue
        out[int(snap.get("rank") or 0)] = snap
    return out


def read_straggler_captures(client, run: str
                            ) -> Dict[int, Dict[str, Any]]:
    out: Dict[int, Dict[str, Any]] = {}
    prefix = f"{run}{_SEP}s:".encode()
    for key in client.kv_keys(KV_SNAP_NS, prefix=prefix):
        blob = client.kv_get(KV_SNAP_NS, key)
        if not blob:
            continue
        try:
            rec = json.loads(blob)
        except ValueError:
            continue
        out[int(rec.get("rank") or 0)] = rec
    return out


def straggler_verdicts(snaps: Dict[int, Dict[str, Any]],
                       multiple: Optional[float] = None,
                       min_steps: Optional[int] = None
                       ) -> Dict[int, Dict[str, Any]]:
    """Pure reducer: per-rank step-phase p95 vs the gang median.

    A rank is a straggler when its p95 exceeds the gang median p95 by
    `multiple` (default config.train_straggler_multiple), with at
    least `min_steps` window samples per participating rank and >= 2
    participating ranks."""
    if multiple is None:
        multiple = float(config.train_straggler_multiple)
    if min_steps is None:
        min_steps = int(config.train_straggler_min_steps)
    p95s: Dict[int, float] = {}
    for rank, snap in snaps.items():
        vals = sorted(
            s["phases"].get("step", 0.0) + s["phases"].get(
                "compile", 0.0)
            for s in (snap.get("window") or [])
            if s.get("phases"))
        if len(vals) >= min_steps:
            p95s[rank] = _percentile(vals, 0.95)
    out: Dict[int, Dict[str, Any]] = {}
    if len(p95s) < 2:
        for rank in snaps:
            out[rank] = {"straggler": False,
                         "p95_s": p95s.get(rank),
                         "median_s": None}
        return out
    med = _median_low(sorted(p95s.values()))
    for rank, p95 in p95s.items():
        out[rank] = {
            "straggler": med > 0 and p95 > multiple * med,
            "p95_s": p95,
            "median_s": med,
            "multiple": (p95 / med) if med > 0 else None,
        }
    for rank in snaps:
        out.setdefault(rank, {"straggler": False, "p95_s": None,
                              "median_s": med})
    return out


def capture_straggler(client, run: str, rank: int,
                      snap: Dict[str, Any],
                      verdict: Dict[str, Any]) -> Optional[str]:
    """ONE targeted stack capture of a flagged worker via the PR-6
    stall-sentinel dump path; the stack is persisted next to the run's
    snapshots, a timeline span records the verdict, and the straggler
    counter bumps.  Returns the captured stack text (or None)."""
    stack = None
    pid = snap.get("pid")
    # Cluster stack keys: bare pid for head-local workers,
    # "pid@<node12>" for remote ones (pids collide across hosts).  A
    # straggler KNOWN to live on a remote node must match its exact
    # pid@node key — falling back to a bare pid there would attach an
    # unrelated head-local process's stack whenever numeric pids
    # collide, misdirecting the diagnosis exactly when the remote
    # node is wedged enough to miss the dump window.
    node12 = (snap.get("node_id") or "")[:12]
    head12 = ""
    try:
        hn = client.node_info().get("node_id")
        head12 = (hn.hex() if isinstance(hn, bytes)
                  else str(hn or ""))[:12]
    except Exception:
        pass
    try:
        reply = client.conn.call({"type": "stack_dump",
                                  "timeout": 5.0, "cluster": True},
                                 timeout=20.0)
        stacks = {str(k): v
                  for k, v in (reply.get("stacks") or {}).items()}
        if node12 and node12 != head12:
            stack = stacks.get(f"{pid}@{node12}")
        else:
            stack = stacks.get(str(pid))
    except Exception:
        pass
    rec = {"run": run, "rank": rank, "ts": time.time(),
           "p95_s": verdict.get("p95_s"),
           "median_s": verdict.get("median_s"),
           "stack": (stack or "")[:8000]}
    try:
        client.kv_put(KV_SNAP_NS, _straggler_key(run, rank),
                      json.dumps(rec).encode())
    except Exception:
        pass
    from ray_tpu._private import tracing
    try:
        now = time.time()
        client.profile_event({
            "name": f"train.straggler[{run}]",
            "start": now, "end": now,
            "pid": os.getpid(), "user": True,
            "trace_id": run_trace_id(run),
            "span_id": tracing.new_span_id(),
            "extra": {"run": run, "rank": rank,
                      "p95_s": verdict.get("p95_s"),
                      "median_s": verdict.get("median_s")},
        })
    except Exception:
        pass
    metrics_mod.shared_counter(
        metrics_mod.TRAIN_STRAGGLERS_METRIC,
        "Gang workers flagged as stragglers by the train reducer",
        tag_keys=("run",)).inc(tags={"run": run})
    return stack


def reset_run(client, run: str,
              trial_dir: Optional[str] = None) -> None:
    """Driver-side, called as a fresh fit() starts: clear any
    PREVIOUS fit's persisted state under this run name.  Without
    this, a reused RunConfig name restores the old fit's ledger and
    step index and charges the entire between-fits gap to
    restart_recovery.  Within-fit worker restarts are unaffected —
    workers construct their telemetry only after this runs.  Passing
    `trial_dir` also clears the report-index counters so the
    telemetry step index and the report ``_step`` stamp restart in
    agreement."""
    try:
        for key in client.kv_keys(KV_SNAP_NS,
                                  prefix=f"{run}{_SEP}".encode()):
            client.kv_del(KV_SNAP_NS, key)
        client.kv_del(KV_RUNS_NS, run.encode())
        if trial_dir:
            for key in client.kv_keys(KV_SEQ_NS,
                                      prefix=f"{trial_dir}:".encode()):
                client.kv_del(KV_SEQ_NS, key)
    except Exception:
        pass


def mark_run_state(client, run: str, state: str) -> None:
    """Driver-side run lifecycle stamp in the runs registry."""
    try:
        blob = client.kv_get(KV_RUNS_NS, run.encode())
        meta = json.loads(blob) if blob else {"run": run}
    except Exception:
        meta = {"run": run}
    meta["state"] = state
    meta["updated_ts"] = time.time()
    try:
        client.kv_put(KV_RUNS_NS, run.encode(),
                      json.dumps(meta).encode())
    except Exception:
        pass


def set_world_size_gauge(run: str, world_size: int) -> None:
    """Driver-side: the run's CURRENT gang size
    (``ray_tpu_train_world_size{run}``).  A per-run series — removed
    by remove_run_gauges when the run finalizes (RT015)."""
    metrics_mod.shared_gauge(
        metrics_mod.TRAIN_WORLD_SIZE_METRIC,
        "Current world size of an elastic train gang",
        tag_keys=("run",)).set(float(world_size), tags={"run": run})


def record_resize(client, run: str, direction: str, old_size: int,
                  new_size: int, step: int,
                  dead_s: float = 0.0) -> None:
    """Driver-side elastic-resize bookkeeping: append the event to the
    run meta (capped history — train status / doctor read it), bump
    ``ray_tpu_train_resizes_total{direction}``, and move the world-size
    gauge.  ``step`` is the checkpoint step the survivors resharded
    from; ``dead_s`` the driver-observed resize dead time."""
    if direction not in ("shrink", "grow"):
        raise ValueError(f"direction {direction!r} not shrink/grow")
    try:
        blob = client.kv_get(KV_RUNS_NS, run.encode())
        meta = json.loads(blob) if blob else {"run": run}
    except Exception:
        meta = {"run": run}
    events = list(meta.get("resizes") or [])
    events.append({"ts": time.time(), "direction": direction,
                   "from": int(old_size), "to": int(new_size),
                   "step": int(step), "dead_s": round(dead_s, 3)})
    meta["resizes"] = events[-32:]       # capped: meta stays small
    meta["resize_count"] = int(meta.get("resize_count") or 0) + 1
    meta["world_size"] = int(new_size)
    meta["updated_ts"] = time.time()
    try:
        client.kv_put(KV_RUNS_NS, run.encode(),
                      json.dumps(meta).encode())
    except Exception:
        pass
    metrics_mod.shared_counter(
        metrics_mod.TRAIN_RESIZES_METRIC,
        "Elastic gang resizes, by direction",
        tag_keys=("direction",)).inc(tags={"direction": direction})
    set_world_size_gauge(run, new_size)


def remove_run_gauges(run: str, force: bool = True) -> None:
    """Zero a run's per-run gauge series even when THIS process never
    wrote them — cross-process cleanup for workers that died uncleanly
    (SIGKILL mid-run: their registry died with them, the node-side
    aggregate would read the last live value forever)."""
    metrics_mod.shared_gauge(
        metrics_mod.TRAIN_MFU_METRIC, tag_keys=("run",)
    ).remove(tags={"run": run}, force=force)
    metrics_mod.shared_gauge(
        metrics_mod.TRAIN_TOKENS_PER_S_METRIC, tag_keys=("run",)
    ).remove(tags={"run": run}, force=force)
    g = metrics_mod.shared_gauge(
        metrics_mod.TRAIN_GOODPUT_FRACTION_METRIC,
        tag_keys=("run", "class"))
    for c in LEDGER_CLASSES:
        g.remove(tags={"run": run, "class": c}, force=force)
    metrics_mod.shared_gauge(
        metrics_mod.TRAIN_WORLD_SIZE_METRIC, tag_keys=("run",)
    ).remove(tags={"run": run}, force=force)


def _bound_verdict(phase_totals: Dict[str, float]) -> Dict[str, Any]:
    active = sum(phase_totals.get(p, 0.0) for p in PHASES)
    if active <= 0:
        return {"bound": "unknown", "verdict": "no steps recorded"}
    frac = {p: phase_totals.get(p, 0.0) / active for p in PHASES}
    if frac["data_wait"] >= float(config.train_input_bound_fraction):
        bound = "input-bound"
        line = (f"input-bound: data_wait "
                f"{frac['data_wait'] * 100:.0f}% of step time")
    elif frac["compile"] >= 0.5:
        bound = "compile-bound"
        line = (f"compile-bound: compile "
                f"{frac['compile'] * 100:.0f}% of step time")
    else:
        bound = "compute-bound"
        line = (f"compute-bound: step "
                f"{frac['step'] * 100:.0f}% of step time")
    return {"bound": bound, "verdict": line}


def summarize_run(meta: Dict[str, Any],
                  snaps: Dict[int, Dict[str, Any]],
                  captures: Optional[Dict[int, Dict[str, Any]]] = None
                  ) -> Dict[str, Any]:
    """Merge one run's worker snapshots into the rollup
    state.train_summary() serves: phase decomposition, goodput
    ledger, live rates, step percentiles, straggler verdicts, and the
    bound verdict line."""
    phases: Dict[str, float] = {p: 0.0 for p in PHASES}
    ledger: Dict[str, float] = {c: 0.0 for c in LEDGER_CLASSES}
    wall = 0.0
    step_index = 0
    tokens_per_s = 0.0
    mfus: List[float] = []
    restarts = 0
    step_samples: List[float] = []
    compile_sites: Dict[str, float] = {}
    ckpt_reads: Dict[str, int] = {"memory": 0, "disk": 0}
    for snap in snaps.values():
        for p, v in (snap.get("phases") or {}).items():
            if p in phases:
                phases[p] += float(v)
        for c, v in (snap.get("ledger") or {}).items():
            if c in ledger:
                ledger[c] += float(v)
        for s, v in (snap.get("compile_sites") or {}).items():
            compile_sites[s] = compile_sites.get(s, 0.0) + float(v)
        for src, v in (snap.get("ckpt_reads") or {}).items():
            if src in ckpt_reads:
                ckpt_reads[src] += int(v)
        wall = max(wall, float(snap.get("wall_s") or 0.0))
        step_index = max(step_index,
                         int(snap.get("step_index") or 0))
        tokens_per_s += float(snap.get("tokens_per_s") or 0.0)
        if snap.get("mfu") is not None:
            mfus.append(float(snap["mfu"]))
        restarts = max(restarts, int(snap.get("restarts") or 0))
        step_samples.extend(
            s.get("wall", 0.0) for s in (snap.get("window") or []))
    n_workers = max(len(snaps), 1)
    # Phase seconds and the ledger are summed over the gang, so the
    # wall-clock denominator is one worker's clock times the number
    # of reporting workers.
    active = sum(phases.values())
    per_worker_wall = wall * len(snaps)
    coverage = (sum(ledger.values()) / per_worker_wall
                if per_worker_wall > 0 else 0.0)
    step_samples.sort()
    out = {
        "run": meta.get("run"),
        "state": meta.get("state", "running"),
        "world_size": meta.get("world_size",
                               max(n_workers, 1)),
        "workers_reporting": len(snaps),
        "restarts": restarts,
        "step_index": step_index,
        "wall_s": wall,
        "phases": {p: {"seconds": round(v, 6),
                       "fraction": (v / active if active > 0
                                    else 0.0)}
                   for p, v in phases.items()},
        "coverage": coverage,
        "ledger": {c: round(v, 6) for c, v in ledger.items()},
        "goodput_fraction": (ledger["productive"] / per_worker_wall
                             if per_worker_wall > 0 else 0.0),
        "tokens_per_s": tokens_per_s,
        "mfu": (sum(mfus) / len(mfus)) if mfus else None,
        "step_ms": {
            "p50": _percentile(step_samples, 0.50) * 1000.0,
            "p95": _percentile(step_samples, 0.95) * 1000.0,
        },
        "stragglers": {
            str(r): v
            for r, v in straggler_verdicts(snaps).items()},
        "ckpt_reads": ckpt_reads,
    }
    # Elastic resize history lives on the run meta (the driver's
    # record_resize writes it): surface it plus the CURRENT gang size
    # so `ray_tpu train status` shows a resize as it happens.
    if meta.get("resizes"):
        out["resizes"] = meta["resizes"]
        out["resize_count"] = int(meta.get("resize_count")
                                  or len(meta["resizes"]))
    if compile_sites:
        # xlasan attribution: the `compile` ledger class broken down
        # by jit construction site, gang-summed, costliest first.
        out["compile_sites"] = {
            s: round(v, 6) for s, v in sorted(
                compile_sites.items(), key=lambda kv: -kv[1])}
    out.update(_bound_verdict(phases))
    if captures:
        out["straggler_captures"] = {
            str(r): {k: rec.get(k) for k in
                     ("ts", "p95_s", "median_s")}
            for r, rec in captures.items()}
    return out
