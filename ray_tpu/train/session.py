"""Per-worker training session context.

Analog of the reference's train session (train/_internal/session.py:111
_TrainSession + ray.train.get_context()): inside a training worker,
user code calls `get_context()` for rank info and `report(metrics,
checkpoint=...)` to stream results to the driver.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint

_context: Optional["TrainContext"] = None


@dataclass
class _Report:
    metrics: Dict[str, Any]
    checkpoint_path: Optional[str] = None


class TrainContext:
    def __init__(self, world_size: int, world_rank: int,
                 trial_dir: str, restore_checkpoint: Optional[str],
                 config: Dict[str, Any],
                 report_ns: Optional[str] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None
                 ) -> None:
        self._dataset_shards = dict(dataset_shards or {})
        self._world_size = world_size
        self._world_rank = world_rank
        self._trial_dir = trial_dir
        self._restore = restore_checkpoint
        self._config = config
        self._reports: List[_Report] = []
        self._lock = threading.Lock()
        self._finished = False
        # Reports are written through to the control plane's KV so they
        # survive worker death (a checkpoint reported the instant before
        # a crash must still be restorable — reference semantics: report
        # is synchronized with the driver, session.py:111).
        self._report_ns = report_ns
        self._seq = 0

    # -- public API (mirrors ray.train context) -------------------------
    def get_world_size(self) -> int:
        return self._world_size

    def get_world_rank(self) -> int:
        return self._world_rank

    def get_trial_dir(self) -> str:
        return self._trial_dir

    def get_config(self) -> Dict[str, Any]:
        return self._config

    def get_checkpoint(self) -> Optional[Checkpoint]:
        """Checkpoint to resume from (set after failure restarts)."""
        if self._restore is None:
            return None
        return Checkpoint(self._restore)

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        rep = _Report(dict(metrics),
                      checkpoint.path if checkpoint else None)
        if self._report_ns is not None:
            import pickle
            from ray_tpu._private.client import get_global_client
            client = get_global_client()
            with self._lock:
                seq = self._seq
                self._seq += 1
            key = f"{self._world_rank:05d}:{seq:09d}".encode()
            client.kv_put(self._report_ns, key,
                          pickle.dumps((rep.metrics, rep.checkpoint_path)))
        else:
            with self._lock:
                self._reports.append(rep)

    # -- driver-facing (drained by trainer polls) ------------------------
    def drain_reports(self) -> List[_Report]:
        with self._lock:
            out, self._reports = self._reports, []
            return out


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError("get_context() called outside a train worker")
    return _context


def set_context(ctx: Optional[TrainContext]) -> None:
    global _context
    _context = ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Module-level convenience mirroring ray.train.report."""
    get_context().report(metrics, checkpoint)


def get_dataset_shard(name: str = "train"):
    """This rank's DataIterator for a Dataset passed to
    TpuTrainer(datasets={name: ds}) (reference:
    ray.train.get_dataset_shard)."""
    ctx = get_context()
    shards = getattr(ctx, "_dataset_shards", None) or {}
    if name not in shards:
        raise KeyError(
            f"no dataset shard named {name!r}; trainer datasets: "
            f"{sorted(shards)}")
    return shards[name]
