"""Per-worker training session context.

Analog of the reference's train session (train/_internal/session.py:111
_TrainSession + ray.train.get_context()): inside a training worker,
user code calls `get_context()` for rank info and `report(metrics,
checkpoint=...)` to stream results to the driver.

The context also owns this worker's telemetry session
(``ctx.telemetry(...)`` -> train/telemetry.py): per-step phase
decomposition, live MFU/goodput, and the published step window the
straggler reducer consumes.  Every ``report()`` is stamped with a
monotonic ``_step`` index and ``_ts`` timestamp; the index is
persisted through the control-plane KV so a resume-from-checkpoint
restart CONTINUES the numbering — timeline spans and metrics agree on
step identity across restarts.
"""

from __future__ import annotations

import os
import time
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint

_context: Optional["TrainContext"] = None


@dataclass
class _Report:
    metrics: Dict[str, Any]
    checkpoint_path: Optional[str] = None


class TrainContext:
    def __init__(self, world_size: int, world_rank: int,
                 trial_dir: str, restore_checkpoint: Optional[str],
                 config: Dict[str, Any],
                 report_ns: Optional[str] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 recovery_class: str = "restart_recovery") -> None:
        self._dataset_shards = dict(dataset_shards or {})
        # Which goodput ledger class this worker's telemetry charges
        # its restore gap to: "restart_recovery" for a fresh attempt,
        # "resize_recovery" for an elastic grow-back replacement.
        self._recovery_class = recovery_class
        self._world_size = world_size
        self._world_rank = world_rank
        self._trial_dir = trial_dir
        self._restore = restore_checkpoint
        self._config = config
        self._reports: List[_Report] = []
        self._lock = threading.Lock()
        self._finished = False
        # Reports are written through to the control plane's KV so they
        # survive worker death (a checkpoint reported the instant before
        # a crash must still be restorable — reference semantics: report
        # is synchronized with the driver, session.py:111).
        self._report_ns = report_ns
        self._seq = 0
        # Monotonic report index stamped onto every report's metrics;
        # restored from the KV on restart so a resumed run keeps
        # counting instead of resetting to 0 (None = not yet loaded).
        # Its OWN lock (not self._lock) serializes hand-out AND the
        # KV write-through as one unit: two racing report() threads
        # must not land their persists out of order, or a restart
        # would restore the stale lower index and mint a duplicate
        # _step — and nothing else ever blocks on this lock, so the
        # held kv_put cannot convoy the report path.
        self._report_index: Optional[int] = None
        self._seq_lock = threading.Lock()
        self._telemetry = None
        self._elastic = None

    # -- public API (mirrors ray.train context) -------------------------
    def get_world_size(self) -> int:
        return self._world_size

    def get_world_rank(self) -> int:
        return self._world_rank

    def get_trial_dir(self) -> str:
        return self._trial_dir

    def get_config(self) -> Dict[str, Any]:
        return self._config

    def get_checkpoint(self) -> Optional[Checkpoint]:
        """Checkpoint to resume from (set after failure restarts)."""
        if self._restore is None:
            return None
        # Disk-read accounting: the elastic storm drill asserts ZERO
        # restart-from-disk by checking this counter stays flat.
        if self._telemetry is not None:
            try:
                self._telemetry.note_ckpt_read("disk")
            except Exception:
                pass
        return Checkpoint(self._restore)

    def telemetry(self, **kwargs):
        """This worker's TrainTelemetry session (created on first
        call; see train/telemetry.py).  The run id is the trial-dir
        basename, shared by every worker and every restart attempt —
        which is what lets the goodput ledger accumulate across
        restarts."""
        if self._telemetry is None:
            from ray_tpu.train import telemetry as telemetry_mod
            run = os.path.basename(
                self._trial_dir.rstrip("/")) or self._trial_dir
            kwargs.setdefault("recovery_class", self._recovery_class)
            self._telemetry = telemetry_mod.TrainTelemetry(
                run, rank=self._world_rank,
                world_size=self._world_size, **kwargs)
        return self._telemetry

    def elastic(self):
        """This worker's ElasticSession (train/elastic.py): gang
        membership, in-cluster sharded checkpoint save/restore, and
        the resize-aware allreduce.  Requires the trainer to be
        running the elastic path (gang record + checkpoint keeper)."""
        if self._elastic is None:
            from ray_tpu.train import elastic as elastic_mod
            run = os.path.basename(
                self._trial_dir.rstrip("/")) or self._trial_dir
            self._elastic = elastic_mod.ElasticSession(
                run, self._world_rank,
                telemetry_provider=lambda: self._telemetry)
        return self._elastic

    def _stop_telemetry(self) -> None:
        tel, self._telemetry = self._telemetry, None
        if tel is not None:
            tel.stop()

    def _next_report_index(self, client) -> int:
        """Monotonic per-rank report index, persisted through the KV
        so a restarted worker CONTINUES the numbering (resume from
        checkpoint must not reset step identity).  The KV ops run
        under _seq_lock ON PURPOSE — persist order must match
        hand-out order, and the lock guards nothing else."""
        from ray_tpu.train.telemetry import KV_SEQ_NS
        key = f"{self._trial_dir}:{self._world_rank}".encode()
        with self._seq_lock:
            if self._report_index is None:
                restore = 0
                if client is not None:
                    try:
                        blob = client.kv_get(   # ray-tpu: noqa[RT011]
                            KV_SEQ_NS, key)
                        restore = int(blob) + 1 if blob else 0
                    except Exception:
                        restore = 0
                self._report_index = restore
            idx = self._report_index
            self._report_index += 1
            if client is not None:
                try:
                    client.kv_put(          # ray-tpu: noqa[RT011]
                        KV_SEQ_NS, key, str(idx).encode())
                except Exception:
                    pass
        return idx

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        client = None
        if self._report_ns is not None:
            from ray_tpu._private.client import get_global_client
            client = get_global_client()
        stamped = dict(metrics)
        if "_step" not in stamped:
            # Guarded, not setdefault: an eagerly-evaluated default
            # would consume (and persist) an index even when the
            # caller re-reports metrics that already carry the stamp.
            stamped["_step"] = self._next_report_index(client)
        if "_ts" not in stamped:
            stamped["_ts"] = time.time()
        rep = _Report(stamped,
                      checkpoint.path if checkpoint else None)
        if self._report_ns is not None:
            import pickle
            with self._lock:
                seq = self._seq
                self._seq += 1
            key = f"{self._world_rank:05d}:{seq:09d}".encode()
            client.kv_put(self._report_ns, key,
                          pickle.dumps((rep.metrics, rep.checkpoint_path)))
        else:
            with self._lock:
                self._reports.append(rep)

    # -- driver-facing (drained by trainer polls) ------------------------
    def drain_reports(self) -> List[_Report]:
        with self._lock:
            out, self._reports = self._reports, []
            return out


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError("get_context() called outside a train worker")
    return _context


def set_context(ctx: Optional[TrainContext]) -> None:
    global _context
    _context = ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Module-level convenience mirroring ray.train.report."""
    get_context().report(metrics, checkpoint)


def get_dataset_shard(name: str = "train"):
    """This rank's DataIterator for a Dataset passed to
    TpuTrainer(datasets={name: ds}) (reference:
    ray.train.get_dataset_shard)."""
    ctx = get_context()
    shards = getattr(ctx, "_dataset_shards", None) or {}
    if name not in shards:
        raise KeyError(
            f"no dataset shard named {name!r}; trainer datasets: "
            f"{sorted(shards)}")
    return shards[name]
