"""`python -m ray_tpu` → the cluster CLI (scripts/cli.py)."""

import sys

from ray_tpu.scripts.cli import main

sys.exit(main())
