// ray_tpu C++ client: a native driver API over the node's TCP control
// endpoint (SURVEY §2.1 N16 — the reference ships a 9k-LoC C++ worker
// API in cpp/; see cpp/README.md for the scope decision here).
//
// Speaks the same length-prefixed message protocol as Python thin
// clients (ray_tpu/_private/protocol.py): each frame is an 8-byte LE
// length + a pickled dict.  Messages are WRITTEN as pickle protocol 2
// (every Python unpickler accepts it) and replies are READ with a
// bounded pickle-opcode VM covering everything the node service emits
// for control replies (ints, floats, bools, None, str, bytes, lists,
// tuples, dicts, memo refs).  Anything outside that — i.e. an
// arbitrary Python object — surfaces as a typed decode error, never a
// silent misread.
//
// Cross-language calls (reference: python/ray/cross_language.py): the
// Python side exports a @remote function under a name
// (ray_tpu.util.cross_lang.export_function); this client looks the
// name up in the GCS KV, submits a task whose args are plain values
// (ints/floats/strings/bytes/lists), and reads back a plain-value
// result.  Values richer than that are a Python<->Python concern by
// design.

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <optional>
#include <stdexcept>
#include <string>
#include <variant>
#include <vector>

namespace ray_tpu {

struct Value;
using ValueList = std::vector<Value>;
using ValueDict = std::vector<std::pair<Value, Value>>;

// A decoded Python value (the bounded control-plane subset).
struct Value {
  // order matters for index(): none, bool, int, float, str, bytes,
  // list, tuple, dict
  std::variant<std::monostate, bool, int64_t, double, std::string,
               std::vector<uint8_t>, std::shared_ptr<ValueList>,
               std::shared_ptr<ValueList>, std::shared_ptr<ValueDict>>
      v;

  bool is_none() const { return v.index() == 0; }
  bool is_bytes() const { return v.index() == 5; }
  bool is_str() const { return v.index() == 4; }
  int64_t as_int() const { return std::get<2>(v); }
  double as_float() const;
  const std::string &as_str() const { return std::get<4>(v); }
  const std::vector<uint8_t> &as_bytes() const { return std::get<5>(v); }
  const ValueList &as_list() const;
  const ValueDict &as_dict() const { return *std::get<8>(v); }
  const Value *dict_get(const std::string &key) const;

  static Value none();
  static Value boolean(bool b);
  static Value integer(int64_t i);
  static Value real(double d);
  static Value str(std::string s);
  static Value bytes(std::vector<uint8_t> b);
  static Value bytes(const void *data, size_t n);
  static Value list(ValueList items);
  static Value tuple(ValueList items);
  static Value dict(ValueDict items);
};

class PickleError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

// Serialize a Value as pickle protocol 2.
std::vector<uint8_t> pickle_dumps(const Value &value);
// Parse a pickle stream (the node's protocol-5 replies included).
Value pickle_loads(const uint8_t *data, size_t size);

// An ObjectRef: the 16-byte id of a task return.
struct ObjectRef {
  std::vector<uint8_t> id;
};

class Client {
 public:
  // Connect to a node's TCP control endpoint (multinode
  // client_address, printed by `python -m ray_tpu start --head`).
  Client(const std::string &host, int port);
  ~Client();

  // -- KV (GCS passthrough) ------------------------------------------
  void kv_put(const std::string &ns, const std::string &key,
              const std::vector<uint8_t> &value);
  std::optional<std::vector<uint8_t>> kv_get(const std::string &ns,
                                             const std::string &key);

  // -- cross-language task calls -------------------------------------
  // Call a Python function exported via
  // ray_tpu.util.cross_lang.export_function(name, fn).
  ObjectRef submit(const std::string &exported_name,
                   const ValueList &args);
  // Block until the task's (plain-value) result is ready.
  Value get(const ObjectRef &ref, double timeout_s = 60.0);

  const std::vector<uint8_t> &client_id() const { return client_id_; }

 private:
  Value call(Value msg, double timeout_s = 60.0);
  void send_frame(const std::vector<uint8_t> &payload);
  std::vector<uint8_t> recv_frame();

  int fd_ = -1;
  int64_t next_req_ = 0;
  std::vector<uint8_t> client_id_;
  std::map<std::string, std::vector<uint8_t>> fn_cache_;
};

}  // namespace ray_tpu
