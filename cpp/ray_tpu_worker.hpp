// ray_tpu C++ WORKER API: register native functions/actors and execute
// tasks submitted from Python (reference: the worker side of the C++
// API, cpp/src/ray/runtime/task/task_executor.cc — native processes
// aren't just drivers).
//
// Values cross the boundary as the plain-value subset (the same
// contract as the reference's msgpack cross-language layer): None,
// bool, int, float, str, bytes, list, dict.  State for native actors
// lives in this process; one connection processes its frames in
// order, so actor-method ordering matches Python actor semantics.
//
// Usage:
//   ray_tpu::Worker w(host, port);
//   w.RegisterFunction("vec_sum", [](const ray_tpu::ValueList &args) {
//     ...; return ray_tpu::Value::integer(total); });
//   w.RegisterActorClass("Counter", [](const ray_tpu::ValueList &args) {
//     return std::make_shared<MyCounter>(args); });
//   w.Run();   // announce + serve until the node goes away
//
// Python side (ray_tpu.util.native):
//   add = native.cpp_function("vec_sum"); ray_tpu.get(add.remote([1,2]))
//   h = native.cpp_actor("Counter").remote(10); h.add.remote(5)

#pragma once

#include <functional>
#include <map>
#include <memory>
#include <string>

#include "ray_tpu_client.hpp"

namespace ray_tpu {

using NativeFn = std::function<Value(const ValueList &)>;

class NativeActor {
 public:
  virtual ~NativeActor() = default;
  virtual Value Call(const std::string &method,
                     const ValueList &args) = 0;
};

using ActorFactory =
    std::function<std::shared_ptr<NativeActor>(const ValueList &)>;

class Worker {
 public:
  Worker(const std::string &host, int port);
  ~Worker();

  void RegisterFunction(const std::string &name, NativeFn fn);
  void RegisterActorClass(const std::string &name, ActorFactory f);

  // Announce the registered names to the node (idempotent; Run calls
  // it if needed).  After it returns, Python submits will route here.
  void Announce();
  // Serve tasks until the connection closes (node shutdown) or
  // `max_tasks` tasks have been executed (max_tasks <= 0: forever).
  void Run(int max_tasks = 0);

 private:
  Value Call(Value msg);
  void SendFrame(const std::vector<uint8_t> &payload);
  std::vector<uint8_t> RecvFrame();
  void Execute(const Value &task);

  int fd_ = -1;
  int64_t next_req_ = 0;
  std::map<std::string, NativeFn> fns_;
  std::map<std::string, ActorFactory> factories_;
  std::map<std::string, std::shared_ptr<NativeActor>> instances_;
  // Tasks that raced the registration reply; drained by Run().
  std::vector<Value> pending_;
  bool announced_ = false;
};

}  // namespace ray_tpu
