// End-to-end smoke driver for the C++ client (run by
// tests/test_cpp_client.py): kv roundtrip + cross-language calls.
#include <cstdio>
#include <cstring>
#include <string>

#include "ray_tpu_client.hpp"

using ray_tpu::Value;

int main(int argc, char **argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: smoke <host> <port>\n");
    return 2;
  }
  ray_tpu::Client client(argv[1], std::atoi(argv[2]));

  // KV roundtrip
  std::string payload = "from-cpp";
  client.kv_put("cpp_smoke", "k1",
                std::vector<uint8_t>(payload.begin(), payload.end()));
  auto got = client.kv_get("cpp_smoke", "k1");
  if (!got.has_value() ||
      std::string(got->begin(), got->end()) != payload) {
    std::fprintf(stderr, "kv roundtrip failed\n");
    return 1;
  }
  if (client.kv_get("cpp_smoke", "absent").has_value()) {
    std::fprintf(stderr, "kv_get absent returned a value\n");
    return 1;
  }

  // Cross-language call: Python-exported add(a, b)
  auto ref = client.submit("add", {Value::integer(20),
                                   Value::integer(22)});
  Value out = client.get(ref, 120.0);
  if (out.as_int() != 42) {
    std::fprintf(stderr, "add returned %lld\n",
                 static_cast<long long>(out.as_int()));
    return 1;
  }

  // Strings + floats + lists
  auto ref2 = client.submit(
      "describe", {Value::str("tpu"), Value::real(2.5)});
  Value d = client.get(ref2, 120.0);
  const Value *msg = d.dict_get("msg");
  const Value *nums = d.dict_get("nums");
  if (msg == nullptr || msg->as_str() != "tpu:2.5" || nums == nullptr ||
      nums->as_list().size() != 3 || nums->as_list()[2].as_int() != 3) {
    std::fprintf(stderr, "describe result mismatch\n");
    return 1;
  }

  // bytes roundtrip through a task
  std::vector<uint8_t> raw = {0, 1, 2, 254, 255};
  auto ref3 = client.submit("echo_bytes", {Value::bytes(raw)});
  Value b = client.get(ref3, 120.0);
  if (b.as_bytes() != raw) {
    std::fprintf(stderr, "bytes roundtrip failed\n");
    return 1;
  }

  std::printf("CPP-SMOKE-OK\n");
  return 0;
}
