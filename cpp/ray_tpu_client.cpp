// Implementation of the ray_tpu C++ client.  See ray_tpu_client.hpp.

#include "ray_tpu_client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <random>

namespace ray_tpu {

// ---------------------------------------------------------------------------
// Value helpers
// ---------------------------------------------------------------------------
namespace {
constexpr size_t kListIdx = 6;
constexpr size_t kTupleIdx = 7;
}  // namespace

double Value::as_float() const {
  if (v.index() == 3) return std::get<3>(v);
  if (v.index() == 2) return static_cast<double>(std::get<2>(v));
  throw PickleError("value is not a number");
}

const ValueList &Value::as_list() const {
  if (v.index() == kListIdx) return *std::get<kListIdx>(v);
  if (v.index() == kTupleIdx) {
    return *std::get<kTupleIdx>(v);
  }
  throw PickleError("value is not a list/tuple");
}

const Value *Value::dict_get(const std::string &key) const {
  for (const auto &kv : as_dict()) {
    if (kv.first.v.index() == 4 && kv.first.as_str() == key)
      return &kv.second;
  }
  return nullptr;
}

Value Value::none() { return Value{}; }
Value Value::boolean(bool b) { Value x; x.v.emplace<1>(b); return x; }
Value Value::integer(int64_t i) { Value x; x.v.emplace<2>(i); return x; }
Value Value::real(double d) { Value x; x.v.emplace<3>(d); return x; }
Value Value::str(std::string s) {
  Value x; x.v.emplace<4>(std::move(s)); return x;
}
Value Value::bytes(std::vector<uint8_t> b) {
  Value x; x.v.emplace<5>(std::move(b)); return x;
}
Value Value::bytes(const void *data, size_t n) {
  const uint8_t *p = static_cast<const uint8_t *>(data);
  return bytes(std::vector<uint8_t>(p, p + n));
}
Value Value::list(ValueList items) {
  Value x;
  x.v.emplace<kListIdx>(std::make_shared<ValueList>(std::move(items)));
  return x;
}
Value Value::tuple(ValueList items) {
  Value x;
  x.v.emplace<kTupleIdx>(std::make_shared<ValueList>(std::move(items)));
  return x;
}
Value Value::dict(ValueDict items) {
  Value x;
  x.v.emplace<8>(std::make_shared<ValueDict>(std::move(items)));
  return x;
}

// ---------------------------------------------------------------------------
// pickle writer (protocol 2)
// ---------------------------------------------------------------------------
namespace {

void put_u32(std::vector<uint8_t> &out, uint32_t n) {
  out.push_back(n & 0xff);
  out.push_back((n >> 8) & 0xff);
  out.push_back((n >> 16) & 0xff);
  out.push_back((n >> 24) & 0xff);
}

void dump_value(std::vector<uint8_t> &out, const Value &val) {
  switch (val.v.index()) {
    case 0:  // None
      out.push_back('N');
      break;
    case 1:  // bool
      out.push_back(std::get<1>(val.v) ? 0x88 : 0x89);
      break;
    case 2: {  // int -> BININT or LONG1
      int64_t i = std::get<2>(val.v);
      if (i >= INT32_MIN && i <= INT32_MAX) {
        out.push_back('J');
        put_u32(out, static_cast<uint32_t>(static_cast<int32_t>(i)));
      } else {
        out.push_back(0x8a);  // LONG1
        out.push_back(8);
        for (int b = 0; b < 8; b++)
          out.push_back((static_cast<uint64_t>(i) >> (8 * b)) & 0xff);
      }
      break;
    }
    case 3: {  // float -> BINFLOAT (big-endian)
      out.push_back('G');
      double d = std::get<3>(val.v);
      uint64_t bits;
      std::memcpy(&bits, &d, 8);
      for (int b = 7; b >= 0; b--) out.push_back((bits >> (8 * b)) & 0xff);
      break;
    }
    case 4: {  // str -> BINUNICODE (must be utf-8)
      const std::string &s = std::get<4>(val.v);
      out.push_back('X');
      put_u32(out, static_cast<uint32_t>(s.size()));
      out.insert(out.end(), s.begin(), s.end());
      break;
    }
    case 5: {  // bytes: protocol-2-compatible via
               // _codecs.encode(latin1_str, 'latin-1')?  Simpler:
               // SHORT_BINBYTES/BINBYTES are protocol 3 — every
               // supported CPython accepts protocol 3 opcodes, so use
               // them (the PROTO header still says 3).
      const auto &b = std::get<5>(val.v);
      if (b.size() < 256) {
        out.push_back('C');  // SHORT_BINBYTES
        out.push_back(static_cast<uint8_t>(b.size()));
      } else {
        out.push_back('B');  // BINBYTES
        put_u32(out, static_cast<uint32_t>(b.size()));
      }
      out.insert(out.end(), b.begin(), b.end());
      break;
    }
    case kListIdx: {
      out.push_back(']');  // EMPTY_LIST
      const auto &items = *std::get<kListIdx>(val.v);
      if (!items.empty()) {
        out.push_back('(');  // MARK
        for (const auto &it : items) dump_value(out, it);
        out.push_back('e');  // APPENDS
      }
      break;
    }
    case kTupleIdx: {
      const auto &items = *std::get<kTupleIdx>(val.v);
      if (items.empty()) {
        out.push_back(')');
      } else if (items.size() == 1) {
        dump_value(out, items[0]);
        out.push_back(0x85);
      } else if (items.size() == 2) {
        dump_value(out, items[0]);
        dump_value(out, items[1]);
        out.push_back(0x86);
      } else if (items.size() == 3) {
        dump_value(out, items[0]);
        dump_value(out, items[1]);
        dump_value(out, items[2]);
        out.push_back(0x87);
      } else {
        out.push_back('(');
        for (const auto &it : items) dump_value(out, it);
        out.push_back('t');
      }
      break;
    }
    case 8: {  // dict
      out.push_back('}');  // EMPTY_DICT
      const auto &items = *std::get<8>(val.v);
      if (!items.empty()) {
        out.push_back('(');
        for (const auto &kv : items) {
          dump_value(out, kv.first);
          dump_value(out, kv.second);
        }
        out.push_back('u');  // SETITEMS
      }
      break;
    }
    default:
      throw PickleError("unserializable value");
  }
}

}  // namespace

std::vector<uint8_t> pickle_dumps(const Value &value) {
  std::vector<uint8_t> out;
  out.push_back(0x80);  // PROTO
  out.push_back(3);     // bytes opcodes need >= 3
  dump_value(out, value);
  out.push_back('.');  // STOP
  return out;
}

// ---------------------------------------------------------------------------
// pickle reader (bounded opcode VM for the node's replies)
// ---------------------------------------------------------------------------
namespace {

struct Reader {
  const uint8_t *p;
  const uint8_t *end;
  std::vector<Value> stack;
  std::vector<size_t> marks;
  std::vector<Value> memo;

  uint8_t u8() {
    if (p >= end) throw PickleError("truncated pickle");
    return *p++;
  }
  uint32_t u32() {
    uint32_t n = 0;
    for (int b = 0; b < 4; b++) n |= static_cast<uint32_t>(u8()) << (8 * b);
    return n;
  }
  uint64_t u64() {
    uint64_t n = 0;
    for (int b = 0; b < 8; b++) n |= static_cast<uint64_t>(u8()) << (8 * b);
    return n;
  }
  const uint8_t *take(size_t n) {
    if (static_cast<size_t>(end - p) < n) throw PickleError("truncated");
    const uint8_t *q = p;
    p += n;
    return q;
  }
  Value pop() {
    if (stack.empty()) throw PickleError("stack underflow");
    Value v = std::move(stack.back());
    stack.pop_back();
    return v;
  }
  std::vector<Value> pop_to_mark() {
    if (marks.empty()) throw PickleError("no mark");
    size_t m = marks.back();
    marks.pop_back();
    std::vector<Value> items(stack.begin() + m, stack.end());
    stack.resize(m);
    return items;
  }
  void memoize() { memo.push_back(stack.back()); }

  Value run() {
    for (;;) {
      uint8_t op = u8();
      switch (op) {
        case 0x80:  // PROTO
          u8();
          break;
        case 0x95:  // FRAME
          u64();
          break;
        case '.':  // STOP
          return pop();
        case 'N':
          stack.push_back(Value::none());
          break;
        case 0x88:
          stack.push_back(Value::boolean(true));
          break;
        case 0x89:
          stack.push_back(Value::boolean(false));
          break;
        case 'J':
          stack.push_back(Value::integer(
              static_cast<int32_t>(u32())));
          break;
        case 'K':
          stack.push_back(Value::integer(u8()));
          break;
        case 'M': {
          uint32_t n = u8();
          n |= static_cast<uint32_t>(u8()) << 8;
          stack.push_back(Value::integer(n));
          break;
        }
        case 0x8a: {  // LONG1
          uint8_t n = u8();
          if (n > 8) throw PickleError("LONG1 too big");
          const uint8_t *q = take(n);
          uint64_t raw = 0;
          for (int b = 0; b < n; b++)
            raw |= static_cast<uint64_t>(q[b]) << (8 * b);
          // sign-extend
          if (n > 0 && (q[n - 1] & 0x80))
            for (int b = n; b < 8; b++) raw |= 0xffULL << (8 * b);
          stack.push_back(Value::integer(static_cast<int64_t>(raw)));
          break;
        }
        case 'G': {  // BINFLOAT big-endian
          uint64_t bits = 0;
          for (int b = 0; b < 8; b++)
            bits = (bits << 8) | u8();
          double d;
          std::memcpy(&d, &bits, 8);
          stack.push_back(Value::real(d));
          break;
        }
        case 0x8c: {  // SHORT_BINUNICODE
          uint8_t n = u8();
          const uint8_t *q = take(n);
          stack.push_back(Value::str(std::string(q, q + n)));
          break;
        }
        case 'X': {  // BINUNICODE
          uint32_t n = u32();
          const uint8_t *q = take(n);
          stack.push_back(Value::str(std::string(q, q + n)));
          break;
        }
        case 'C': {  // SHORT_BINBYTES
          uint8_t n = u8();
          const uint8_t *q = take(n);
          stack.push_back(Value::bytes(q, n));
          break;
        }
        case 'B': {  // BINBYTES
          uint32_t n = u32();
          const uint8_t *q = take(n);
          stack.push_back(Value::bytes(q, n));
          break;
        }
        case 0x8e: {  // BINBYTES8
          uint64_t n = u64();
          const uint8_t *q = take(n);
          stack.push_back(Value::bytes(q, n));
          break;
        }
        case 0x96: {  // BYTEARRAY8 (protocol 5) — the node ships
          // bytearray-backed payloads on zero-copy paths; decode
          // them exactly like bytes.
          uint64_t n = u64();
          const uint8_t *q = take(n);
          stack.push_back(Value::bytes(q, n));
          break;
        }
        case ']':
          stack.push_back(Value::list({}));
          break;
        case ')':
          stack.push_back(Value::tuple({}));
          break;
        case '}':
          stack.push_back(Value::dict({}));
          break;
        case '(':
          marks.push_back(stack.size());
          break;
        case 'a': {  // APPEND
          Value item = pop();
          std::get<kListIdx>(stack.back().v)->push_back(std::move(item));
          break;
        }
        case 'e': {  // APPENDS
          auto items = pop_to_mark();
          auto &lst = *std::get<kListIdx>(stack.back().v);
          for (auto &it : items) lst.push_back(std::move(it));
          break;
        }
        case 's': {  // SETITEM
          Value val = pop();
          Value key = pop();
          std::get<8>(stack.back().v)
              ->emplace_back(std::move(key), std::move(val));
          break;
        }
        case 'u': {  // SETITEMS
          auto items = pop_to_mark();
          auto &d = *std::get<8>(stack.back().v);
          for (size_t i = 0; i + 1 < items.size(); i += 2)
            d.emplace_back(std::move(items[i]), std::move(items[i + 1]));
          break;
        }
        case 't': {  // TUPLE
          auto items = pop_to_mark();
          stack.push_back(Value::tuple(std::move(items)));
          break;
        }
        case 0x85: {  // TUPLE1
          Value a = pop();
          stack.push_back(Value::tuple({std::move(a)}));
          break;
        }
        case 0x86: {  // TUPLE2
          Value b = pop();
          Value a = pop();
          stack.push_back(Value::tuple({std::move(a), std::move(b)}));
          break;
        }
        case 0x87: {  // TUPLE3
          Value c = pop();
          Value b = pop();
          Value a = pop();
          stack.push_back(
              Value::tuple({std::move(a), std::move(b), std::move(c)}));
          break;
        }
        case 0x94:  // MEMOIZE
          memoize();
          break;
        case 'q':  // BINPUT
          u8();
          memoize();
          break;
        case 'r':  // LONG_BINPUT
          u32();
          memoize();
          break;
        case 'h': {  // BINGET
          uint8_t i = u8();
          if (i >= memo.size()) throw PickleError("bad memo index");
          stack.push_back(memo[i]);
          break;
        }
        case 'j': {  // LONG_BINGET
          uint32_t i = u32();
          if (i >= memo.size()) throw PickleError("bad memo index");
          stack.push_back(memo[i]);
          break;
        }
        default:
          throw PickleError(
              "unsupported pickle opcode 0x" +
              std::to_string(static_cast<int>(op)) +
              " (reply holds a non-plain Python object)");
      }
    }
  }
};

}  // namespace

Value pickle_loads(const uint8_t *data, size_t size) {
  Reader r{data, data + size, {}, {}, {}};
  return r.run();
}

// ---------------------------------------------------------------------------
// RTO1 object framing (ray_tpu/_private/serialization.py)
// ---------------------------------------------------------------------------
namespace {

Value decode_rto1(const std::vector<uint8_t> &blob) {
  if (blob.size() < 16 || std::memcmp(blob.data(), "RTO1", 4) != 0)
    throw PickleError("bad object header");
  uint32_t n_buffers;
  uint64_t inband_len;
  std::memcpy(&n_buffers, blob.data() + 4, 4);
  std::memcpy(&inband_len, blob.data() + 8, 8);
  if (n_buffers != 0)
    throw PickleError(
        "result holds out-of-band buffers (numpy/large-bytes) — "
        "cross-language results must be plain values");
  size_t pos = 16;
  if (blob.size() < pos + inband_len) throw PickleError("truncated object");
  return pickle_loads(blob.data() + pos, inband_len);
}

std::vector<uint8_t> encode_rto1(const Value &value) {
  std::vector<uint8_t> inband = pickle_dumps(value);
  std::vector<uint8_t> out(16);
  std::memcpy(out.data(), "RTO1", 4);
  uint32_t zero = 0;
  uint64_t n = inband.size();
  std::memcpy(out.data() + 4, &zero, 4);
  std::memcpy(out.data() + 8, &n, 8);
  out.insert(out.end(), inband.begin(), inband.end());
  return out;
}

std::vector<uint8_t> random_id() {
  static std::random_device rd;
  std::vector<uint8_t> id(16);
  for (auto &b : id) b = static_cast<uint8_t>(rd());
  return id;
}

}  // namespace

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------
Client::Client(const std::string &host, int port) {
  client_id_ = random_id();
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) throw std::runtime_error("socket() failed");
  int one = 1;
  ::setsockopt(fd_, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    hostent *he = ::gethostbyname(host.c_str());
    if (he == nullptr) throw std::runtime_error("resolve failed: " + host);
    std::memcpy(&addr.sin_addr, he->h_addr, sizeof(addr.sin_addr));
  }
  if (::connect(fd_, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) != 0)
    throw std::runtime_error("connect failed");
  Value reply = call(Value::dict({
      {Value::str("type"), Value::str("register_client")},
      {Value::str("kind"), Value::str("driver")},
      {Value::str("client_id"), Value::bytes(client_id_)},
      {Value::str("pid"), Value::integer(::getpid())},
  }));
  if (reply.dict_get("session_dir") == nullptr)
    throw std::runtime_error("register_client: unexpected reply");
}

Client::~Client() {
  if (fd_ >= 0) ::close(fd_);
}

void Client::send_frame(const std::vector<uint8_t> &payload) {
  uint64_t n = payload.size();
  uint8_t hdr[8];
  std::memcpy(hdr, &n, 8);
  std::vector<uint8_t> buf(hdr, hdr + 8);
  buf.insert(buf.end(), payload.begin(), payload.end());
  size_t off = 0;
  while (off < buf.size()) {
    ssize_t w = ::send(fd_, buf.data() + off, buf.size() - off, 0);
    if (w <= 0) throw std::runtime_error("send failed");
    off += static_cast<size_t>(w);
  }
}

std::vector<uint8_t> Client::recv_frame() {
  uint8_t hdr[8];
  size_t got = 0;
  while (got < 8) {
    ssize_t r = ::recv(fd_, hdr + got, 8 - got, 0);
    if (r <= 0) throw std::runtime_error("recv failed");
    got += static_cast<size_t>(r);
  }
  uint64_t n;
  std::memcpy(&n, hdr, 8);
  std::vector<uint8_t> out(n);
  got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd_, out.data() + got, n - got, 0);
    if (r <= 0) throw std::runtime_error("recv failed");
    got += static_cast<size_t>(r);
  }
  return out;
}

Value Client::call(Value msg, double /*timeout_s*/) {
  int64_t req = ++next_req_;
  std::get<8>(msg.v)->emplace_back(Value::str("__req_id__"),
                                   Value::integer(req));
  send_frame(pickle_dumps(msg));
  for (;;) {
    std::vector<uint8_t> frame = recv_frame();
    Value reply;
    try {
      reply = pickle_loads(frame.data(), frame.size());
    } catch (const PickleError &) {
      // Undecodable frame.  If it carries "__reply_to__" it is a
      // solicited reply whose payload holds a rich Python object —
      // which on the control plane means {"__error__": Exception}.
      // This client is strictly one-request-at-a-time, so that reply
      // is ours: fail loudly instead of waiting forever for a frame
      // that will never come.  Frames WITHOUT the marker are
      // unsolicited pushes (log batches etc.): skip them.
      static const std::string marker = "__reply_to__";
      if (std::search(frame.begin(), frame.end(), marker.begin(),
                      marker.end()) != frame.end())
        throw std::runtime_error(
            "rpc failed with a Python exception (reply not "
            "plain-value decodable; see server logs)");
      continue;
    }
    if (reply.v.index() != 8) continue;
    const Value *rid = reply.dict_get("__reply_to__");
    if (rid == nullptr || rid->as_int() != req) continue;  // push/stale
    const Value *err = reply.dict_get("__error__");
    if (err != nullptr && !err->is_none())
      throw std::runtime_error(
          "rpc error: " + (err->is_str() ? err->as_str()
                                         : std::string("python exception")));
    return reply;
  }
}

void Client::kv_put(const std::string &ns, const std::string &key,
                    const std::vector<uint8_t> &value) {
  call(Value::dict({
      {Value::str("type"), Value::str("kv_put")},
      {Value::str("ns"), Value::str(ns)},
      {Value::str("key"), Value::bytes(key.data(), key.size())},
      {Value::str("value"), Value::bytes(value)},
      {Value::str("overwrite"), Value::boolean(true)},
  }));
}

std::optional<std::vector<uint8_t>> Client::kv_get(const std::string &ns,
                                                   const std::string &key) {
  Value reply = call(Value::dict({
      {Value::str("type"), Value::str("kv_get")},
      {Value::str("ns"), Value::str(ns)},
      {Value::str("key"), Value::bytes(key.data(), key.size())},
  }));
  const Value *v = reply.dict_get("value");
  if (v == nullptr || v->is_none()) return std::nullopt;
  return v->as_bytes();
}

ObjectRef Client::submit(const std::string &exported_name,
                         const ValueList &args) {
  auto it = fn_cache_.find(exported_name);
  if (it == fn_cache_.end()) {
    auto fid = kv_get("cross_lang", exported_name);
    if (!fid.has_value())
      throw std::runtime_error("no exported function named '" +
                               exported_name + "'");
    it = fn_cache_.emplace(exported_name, *fid).first;
  }
  // args blob: ((positional...), ref_slots=[], kw_ref_items=[],
  // plain_kwargs={}) in the RTO1 framing (_pack_args wire format).
  Value payload = Value::tuple({Value::list(args), Value::list({}),
                                Value::list({}), Value::dict({})});
  std::vector<uint8_t> blob = encode_rto1(payload);
  ObjectRef ref{random_id()};
  Value spec = Value::dict({
      {Value::str("task_id"), Value::bytes(random_id())},
      {Value::str("name"), Value::str(exported_name)},
      {Value::str("function_id"), Value::bytes(it->second)},
      {Value::str("args"),
       Value::list({Value::tuple(
           {Value::str("inline"), Value::bytes(std::move(blob))})})},
      {Value::str("embedded"), Value::list({})},
      {Value::str("num_returns"), Value::integer(1)},
      {Value::str("return_ids"),
       Value::list({Value::bytes(ref.id)})},
      {Value::str("resources"), Value::dict({})},
      {Value::str("retries"), Value::integer(0)},
      {Value::str("actor_id"), Value::none()},
      {Value::str("owner"), Value::bytes(client_id_)},
      {Value::str("pg"), Value::none()},
  });
  // One-way submit (no __req_id__), same as the Python client.
  send_frame(pickle_dumps(Value::dict({
      {Value::str("type"), Value::str("submit_task")},
      {Value::str("spec"), std::move(spec)},
  })));
  return ref;
}

Value Client::get(const ObjectRef &ref, double timeout_s) {
  Value reply = call(
      Value::dict({
          {Value::str("type"), Value::str("get_objects")},
          {Value::str("object_ids"),
           Value::list({Value::bytes(ref.id)})},
          {Value::str("timeout"), Value::real(timeout_s)},
      }),
      timeout_s + 15.0);
  const Value *timed_out = reply.dict_get("timed_out");
  if (timed_out != nullptr && timed_out->v.index() == 1 &&
      std::get<1>(timed_out->v))
    throw std::runtime_error("get() timed out");
  const Value *results = reply.dict_get("results");
  if (results == nullptr) throw std::runtime_error("malformed reply");
  for (const auto &kv : results->as_dict()) {
    const ValueList &t = kv.second.as_list();  // (loc, data, size)
    const std::string &loc = t.at(0).as_str();
    if (loc == "error")
      throw std::runtime_error("task failed (Python exception; see logs)");
    if (loc != "inline")
      throw std::runtime_error(
          "result too large for the cross-language inline path (loc=" +
          loc + ")");
    return decode_rto1(t.at(1).as_bytes());
  }
  throw std::runtime_error("empty get_objects reply");
}

}  // namespace ray_tpu
