// Demo/test binary for the C++ worker API: registers a native
// function and a stateful native actor, then serves tasks.
// Driven end-to-end by tests/test_cpp_worker.py.

#include <cstdio>
#include <cstdlib>
#include <numeric>

#include "ray_tpu_worker.hpp"

using ray_tpu::NativeActor;
using ray_tpu::Value;
using ray_tpu::ValueList;

namespace {

// Sum a list of ints/floats plus an optional scalar bias.
Value VecSum(const ValueList &args) {
  double total = 0;
  bool all_int = true;
  if (!args.empty()) {
    for (const Value &v : args[0].as_list()) {
      if (v.v.index() == 2) {
        total += static_cast<double>(v.as_int());
      } else {
        total += v.as_float();
        all_int = false;
      }
    }
  }
  if (args.size() > 1) {
    if (args[1].v.index() == 2) {
      total += static_cast<double>(args[1].as_int());
    } else {
      total += args[1].as_float();
      all_int = false;
    }
  }
  if (all_int) return Value::integer(static_cast<int64_t>(total));
  return Value::real(total);
}

Value Describe(const ValueList &args) {
  const std::string &name = args[0].as_str();
  return Value::dict({
      {Value::str("greeting"), Value::str("hello " + name)},
      {Value::str("lang"), Value::str("cpp")},
      {Value::str("args_seen"),
       Value::integer(static_cast<int64_t>(args.size()))},
  });
}

class Counter : public NativeActor {
 public:
  explicit Counter(int64_t start) : total_(start) {}

  Value Call(const std::string &method,
             const ValueList &args) override {
    if (method == "add") {
      total_ += args[0].as_int();
      return Value::integer(total_);
    }
    if (method == "total") return Value::integer(total_);
    throw std::runtime_error("Counter has no method: " + method);
  }

 private:
  int64_t total_;
};

}  // namespace

int main(int argc, char **argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <host> <port> [max_tasks]\n",
                 argv[0]);
    return 2;
  }
  int max_tasks = argc > 3 ? std::atoi(argv[3]) : 0;
  try {
    ray_tpu::Worker w(argv[1], std::atoi(argv[2]));
    w.RegisterFunction("vec_sum", VecSum);
    w.RegisterFunction("describe", Describe);
    w.RegisterActorClass("Counter", [](const ValueList &args) {
      int64_t start = args.empty() ? 0 : args[0].as_int();
      return std::make_shared<Counter>(start);
    });
    w.Announce();
    std::printf("CPP-WORKER-READY\n");
    std::fflush(stdout);
    w.Run(max_tasks);
  } catch (const std::exception &e) {
    std::fprintf(stderr, "worker failed: %s\n", e.what());
    return 1;
  }
  return 0;
}
