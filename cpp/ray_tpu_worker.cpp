// Implementation of the ray_tpu C++ worker (see ray_tpu_worker.hpp).
// Framing + pickle codecs come from ray_tpu_client.cpp; the socket
// plumbing is intentionally re-stated here (the Client keeps its fd
// private, and the worker's serve loop owns its connection lifecycle).

#include "ray_tpu_worker.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <random>
#include <stdexcept>

namespace ray_tpu {

namespace {
std::vector<uint8_t> random_id16() {
  std::random_device rd;
  std::vector<uint8_t> id(16);
  for (auto &b : id) b = static_cast<uint8_t>(rd());
  return id;
}

std::string hex(const std::vector<uint8_t> &b) {
  static const char *d = "0123456789abcdef";
  std::string out;
  for (uint8_t x : b) {
    out.push_back(d[x >> 4]);
    out.push_back(d[x & 15]);
  }
  return out;
}
}  // namespace

Worker::Worker(const std::string &host, int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) throw std::runtime_error("socket() failed");
  int one = 1;
  ::setsockopt(fd_, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    hostent *he = ::gethostbyname(host.c_str());
    if (he == nullptr)
      throw std::runtime_error("resolve failed: " + host);
    std::memcpy(&addr.sin_addr, he->h_addr, sizeof(addr.sin_addr));
  }
  if (::connect(fd_, reinterpret_cast<sockaddr *>(&addr),
                sizeof(addr)) != 0)
    throw std::runtime_error("connect failed");
  Value reply = Call(Value::dict({
      {Value::str("type"), Value::str("register_client")},
      {Value::str("kind"), Value::str("driver")},
      {Value::str("client_id"), Value::bytes(random_id16())},
      {Value::str("pid"), Value::integer(::getpid())},
  }));
  if (reply.dict_get("session_dir") == nullptr)
    throw std::runtime_error("register_client: unexpected reply");
}

Worker::~Worker() {
  if (fd_ >= 0) ::close(fd_);
}

void Worker::RegisterFunction(const std::string &name, NativeFn fn) {
  fns_[name] = std::move(fn);
}

void Worker::RegisterActorClass(const std::string &name,
                                ActorFactory f) {
  factories_[name] = std::move(f);
}

void Worker::SendFrame(const std::vector<uint8_t> &payload) {
  uint64_t n = payload.size();
  uint8_t hdr[8];
  std::memcpy(hdr, &n, 8);
  std::vector<uint8_t> buf(hdr, hdr + 8);
  buf.insert(buf.end(), payload.begin(), payload.end());
  size_t off = 0;
  while (off < buf.size()) {
    ssize_t w = ::send(fd_, buf.data() + off, buf.size() - off, 0);
    if (w <= 0) throw std::runtime_error("send failed");
    off += static_cast<size_t>(w);
  }
}

std::vector<uint8_t> Worker::RecvFrame() {
  uint8_t hdr[8];
  size_t got = 0;
  while (got < 8) {
    ssize_t r = ::recv(fd_, hdr + got, 8 - got, 0);
    if (r <= 0) throw std::runtime_error("connection closed");
    got += static_cast<size_t>(r);
  }
  uint64_t n;
  std::memcpy(&n, hdr, 8);
  std::vector<uint8_t> out(n);
  got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd_, out.data() + got, n - got, 0);
    if (r <= 0) throw std::runtime_error("connection closed");
    got += static_cast<size_t>(r);
  }
  return out;
}

Value Worker::Call(Value msg) {
  int64_t req = ++next_req_;
  std::get<8>(msg.v)->emplace_back(Value::str("__req_id__"),
                                   Value::integer(req));
  SendFrame(pickle_dumps(msg));
  for (;;) {
    std::vector<uint8_t> frame = RecvFrame();
    Value reply;
    try {
      reply = pickle_loads(frame.data(), frame.size());
    } catch (const PickleError &) {
      // Undecodable frame: if it carries "__reply_to__" it is a
      // solicited reply holding a rich Python object — on this plane
      // that means {"__error__": Exception} (e.g. duplicate function
      // registration).  Call() is one-request-at-a-time, so that
      // reply is ours: fail loudly instead of hanging in RecvFrame()
      // for a reply that already arrived.  Marker-less frames are
      // unsolicited pushes: skip them.  (The request id inside an
      // undecodable frame cannot be checked; a stale abandoned reply
      // could in principle fail the NEXT call — but an abandoned
      // reply only exists if a previous Call already threw here, so
      // the connection is degraded either way and a loud error beats
      // a silent deadlock.)
      static const std::string marker = "__reply_to__";
      if (std::search(frame.begin(), frame.end(), marker.begin(),
                      marker.end()) != frame.end())
        throw std::runtime_error(
            "rpc failed with a Python exception (reply not "
            "plain-value decodable; see node logs)");
      continue;
    }
    if (reply.v.index() != 8) continue;
    const Value *rid = reply.dict_get("__reply_to__");
    if (rid == nullptr || rid->as_int() != req) {
      // A task can land BEFORE the registration reply (the node
      // publishes names under its lock, then replies): buffer it for
      // Run() instead of dropping it on the floor.
      const Value *type = reply.dict_get("type");
      if (type != nullptr && type->is_str() &&
          (type->as_str() == "native_task" ||
           type->as_str() == "native_actor_release"))
        pending_.push_back(std::move(reply));
      continue;
    }
    const Value *err = reply.dict_get("__error__");
    if (err != nullptr)
      throw std::runtime_error(
          "rpc error: " + (err->is_str() ? err->as_str()
                                         : std::string("<exception>")));
    return reply;
  }
}

void Worker::Execute(const Value &task) {
  const Value *tid = task.dict_get("task_id");
  if (tid == nullptr) return;
  ValueDict done{{Value::str("type"), Value::str("native_done")},
                 {Value::str("task_id"), Value::bytes(tid->as_bytes())}};
  try {
    const std::string kind = task.dict_get("kind")->as_str();
    ValueList args;
    const Value *a = task.dict_get("args");
    if (a != nullptr && (a->v.index() == 6 || a->v.index() == 7))
      args = a->as_list();
    Value result = Value::none();
    if (kind == "fn") {
      const std::string name = task.dict_get("name")->as_str();
      auto it = fns_.find(name);
      if (it == fns_.end())
        throw std::runtime_error("unknown native function: " + name);
      result = it->second(args);
    } else if (kind == "actor_create") {
      const std::string name = task.dict_get("name")->as_str();
      auto it = factories_.find(name);
      if (it == factories_.end())
        throw std::runtime_error("unknown native actor class: " + name);
      std::string iid = hex(task.dict_get("instance")->as_bytes());
      instances_[iid] = it->second(args);
      result = Value::none();
    } else if (kind == "actor_method") {
      std::string iid = hex(task.dict_get("instance")->as_bytes());
      auto it = instances_.find(iid);
      if (it == instances_.end())
        throw std::runtime_error("unknown native actor instance");
      result = it->second->Call(task.dict_get("method")->as_str(),
                                args);
    } else {
      throw std::runtime_error("unknown native task kind: " + kind);
    }
    done.emplace_back(Value::str("value"), result);
  } catch (const std::exception &e) {
    done.emplace_back(Value::str("error"),
                      Value::str(std::string(e.what())));
  }
  SendFrame(pickle_dumps(Value::dict(std::move(done))));
}

void Worker::Announce() {
  if (announced_) return;
  ValueList fn_names, actor_names;
  for (const auto &kv : fns_) fn_names.push_back(Value::str(kv.first));
  for (const auto &kv : factories_)
    actor_names.push_back(Value::str(kv.first));
  Call(Value::dict({
      {Value::str("type"), Value::str("register_native_worker")},
      {Value::str("language"), Value::str("cpp")},
      {Value::str("functions"), Value::list(std::move(fn_names))},
      {Value::str("actors"), Value::list(std::move(actor_names))},
  }));
  announced_ = true;
}

void Worker::Run(int max_tasks) {
  Announce();
  int executed = 0;
  auto handle = [&](const Value &msg) -> bool {
    const Value *type = msg.dict_get("type");
    if (type == nullptr || !type->is_str()) return false;
    if (type->as_str() == "native_actor_release") {
      const Value *inst = msg.dict_get("instance");
      if (inst != nullptr) instances_.erase(hex(inst->as_bytes()));
      return false;
    }
    if (type->as_str() != "native_task") return false;
    Execute(msg);
    return true;
  };
  // Buffered during registration.  Consume entries as they execute:
  // an early max_tasks return must not leave executed tasks in
  // pending_, or the next Run() would replay their side effects.
  while (!pending_.empty()) {
    Value msg = std::move(pending_.front());
    pending_.erase(pending_.begin());
    if (handle(msg) && max_tasks > 0 && ++executed >= max_tasks)
      return;
  }
  for (;;) {
    std::vector<uint8_t> frame;
    try {
      frame = RecvFrame();
    } catch (const std::exception &) {
      return;  // node gone: a worker's lifetime is its connection's
    }
    Value msg;
    try {
      msg = pickle_loads(frame.data(), frame.size());
    } catch (const PickleError &) {
      continue;  // non-plain push (log batch etc.): not for us
    }
    if (msg.v.index() != 8) continue;
    if (handle(msg) && max_tasks > 0 && ++executed >= max_tasks)
      return;
  }
}

}  // namespace ray_tpu
