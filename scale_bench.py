"""Scalability-envelope harness: control-plane throughput vs node count.

Analog of the reference's standing envelope suite
(release/benchmarks/README.md:7-12 — many_nodes/many_actors/many_pgs —
with results checked into release/release_logs/<version>/benchmarks/).
Runs against the in-process virtual cluster (cluster_utils.Cluster: a
real GCS + N real node-service subprocesses on this host), so the
numbers measure the CONTROL PLANE — scheduling, dispatch, GCS, PG 2PC
— not worker compute.

Measures, at 1/2/4/8 virtual nodes:
  * tasks/s          — drain N no-op tasks spread over the cluster
  * actors/s         — create+ping K actors, then kill
  * pg create/remove — sequential placement-group 2PC latency
plus a 200-actor churn (create/kill loop) at the largest size.

Writes SCALE_<round>.json (SCALE_ROUND env, default r07) and prints
one JSON line.  tests/test_scale_envelope.py runs a shrunk version as
the CI regression gate.  Reference baselines for orientation (64-node
cluster, BASELINE.md): 334-589 tasks/s, 580 actors/s, PG 0.91/0.86 ms.

Focused microbench legs (each writes into MICROBENCH_<round>.json):
  SCALE_DAG=1              compiled-graph per-hop overhead
  SCALE_OBJECT_TRANSFER=1  windowed binary object pull
  SCALE_SCHED=1            scheduler placement throughput + decision
                           latency p50/p95 on a 2-node cluster
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List


def measure_tasks(ray_tpu, n: int) -> float:
    @ray_tpu.remote
    def noop(i):
        return i

    # warm the worker pools
    ray_tpu.get([noop.remote(i) for i in range(8)])
    t0 = time.perf_counter()
    ray_tpu.get([noop.remote(i) for i in range(n)])
    return n / (time.perf_counter() - t0)


def measure_actors(ray_tpu, k: int) -> float:
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(k)]
    ray_tpu.get([a.ping.remote() for a in actors])
    rate = k / (time.perf_counter() - t0)
    for a in actors:
        ray_tpu.kill(a)
    return rate


def measure_pg(ray_tpu, n: int) -> Dict[str, float]:
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    create_s = 0.0
    remove_s = 0.0
    for _ in range(n):
        t0 = time.perf_counter()
        pg = placement_group([{"CPU": 0.01}], strategy="PACK")
        ray_tpu.get(pg.ready())
        create_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        remove_placement_group(pg)
        remove_s += time.perf_counter() - t0
    return {"pg_create_ms": round(create_s / n * 1e3, 2),
            "pg_remove_ms": round(remove_s / n * 1e3, 2)}


def measure_actor_churn(ray_tpu, total: int, batch: int = 50) -> float:
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    done = 0
    while done < total:
        k = min(batch, total - done)
        actors = [A.remote() for _ in range(k)]
        ray_tpu.get([a.ping.remote() for a in actors])
        for a in actors:
            ray_tpu.kill(a)
        done += k
    return total / (time.perf_counter() - t0)


def measure_object_transfer(size_mb: int = 256) -> dict:
    """Inter-node object-transfer throughput on a loopback two-node
    cluster: one `size_mb` object produced on the worker node, pulled
    by the head (driver) node — window=1 (the stop-and-wait
    control-plane baseline) vs the default windowed binary stream.
    Reported as MB/s of the driver-side get()."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.config import config as _cfg
    from ray_tpu.cluster_utils import Cluster

    store = (size_mb + 192) * 1024 * 1024
    cluster = Cluster()
    cluster.add_node(resources={"CPU": 2.0, "remote": 1.0},
                     store_capacity=2 * store)
    ray_tpu.init(num_cpus=2, gcs_address=cluster.gcs_address,
                 object_store_memory=2 * store)
    out: dict = {"object_mb": size_mb}
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(resources={"remote": 1}, num_returns=2)
        def produce(n):
            return np.arange(n // 8, dtype=np.float64), "done"

        def one_pull() -> float:
            big_ref, done_ref = produce.remote(size_mb << 20)
            # The small sentinel proves the big object is produced
            # remotely WITHOUT arming a pull for it: the measured get()
            # below is pure transfer.
            assert ray_tpu.get(done_ref, timeout=120) == "done"
            t0 = time.perf_counter()
            arr = ray_tpu.get(big_ref, timeout=300)
            dt = time.perf_counter() - t0
            assert arr[4096] == 4096.0
            del arr, big_ref, done_ref
            time.sleep(0.5)     # let the freed objects drain
            return size_mb / dt

        # warm both worker pools + the peer connection
        ray_tpu.get(list(produce.remote(1 << 20)), timeout=120)
        default_window = _cfg.object_transfer_window
        _cfg.set("object_transfer_window", 1)
        try:
            out["window1_mb_s"] = round(one_pull(), 1)
        finally:
            _cfg.set("object_transfer_window", default_window)
        out["windowed_mb_s"] = round(one_pull(), 1)
        out["window"] = _cfg.object_transfer_window
        out["speedup"] = round(out["windowed_mb_s"]
                               / max(out["window1_mb_s"], 1e-9), 2)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    return out


def _percentiles_us(lat_s: List[float], hops: int) -> Dict[str, float]:
    import numpy as np
    arr = np.asarray(sorted(lat_s)) * 1e6
    return {
        "round_trip_us_p50": round(float(np.percentile(arr, 50)), 1),
        "round_trip_us_p95": round(float(np.percentile(arr, 95)), 1),
        "per_hop_us_p50": round(float(np.percentile(arr, 50)) / hops, 1),
        "per_hop_us_p95": round(float(np.percentile(arr, 95)) / hops, 1),
        "hops": hops,
    }


def _measure_compiled_chain(ray_tpu, actors, iters: int,
                            warm: int) -> Dict[str, float]:
    """Compiled actor chain, two views: serial execute+get round trips
    (latency; per-hop = round trip / edges) and a pipelined window of
    in-flight executes (throughput; per-hop = wall / items / edges —
    the steady-state overhead the fast lane is built for: waits
    overlap, every stage's channel poll stays in its spin budget)."""
    from ray_tpu.dag import InputNode
    hops = len(actors) + 1
    with InputNode() as inp:
        out = inp
        for a in actors:
            out = a.step.bind(out)
    dag = out.experimental_compile(capacity=16)
    try:
        for _ in range(warm):
            assert dag.execute(1).get(timeout=60) == 1
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter()
            dag.execute(1).get(timeout=60)
            lat.append(time.perf_counter() - t0)
        # Pipelined: a sliding window of 8 in-flight executes.  The
        # steady-state per-hop overhead — the number the fast lane is
        # built for — is the p50/p95 of inter-completion times over
        # the edge count (waits overlap across stages, so every
        # stage's channel poll stays inside its spin budget).
        window, pending = 8, []
        t0 = time.perf_counter()
        last = None
        deltas = []
        for i in range(iters):
            pending.append(dag.execute(1))
            if len(pending) >= window:
                pending.pop(0).get(timeout=60)
                now = time.perf_counter()
                if last is not None:
                    deltas.append(now - last)
                last = now
        for r in pending:
            r.get(timeout=60)
        wall = time.perf_counter() - t0
    finally:
        dag.teardown()
    res = {f"serial_{k}": v
           for k, v in _percentiles_us(lat, hops).items()}
    piped = _percentiles_us(deltas, hops)
    res.update({
        "hops": hops,
        "per_hop_us_p50": piped["per_hop_us_p50"],
        "per_hop_us_p95": piped["per_hop_us_p95"],
        "pipelined_items_per_s": round(iters / wall, 1),
    })
    return res


def _measure_legacy_chain(ray_tpu, actors, iters: int,
                          warm: int) -> Dict[str, float]:
    """The per-call baseline: the same chain as chained actor tasks
    (each hop pays Python scheduling + dispatch), measured the same
    two ways — serial round trips and a pipelined window of chains —
    and normalized to the same hop count."""
    hops = len(actors) + 1

    def submit():
        ref = 1
        for a in actors:
            ref = a.step.remote(ref)
        return ref

    def once() -> float:
        t0 = time.perf_counter()
        ray_tpu.get(submit(), timeout=60)
        return time.perf_counter() - t0

    for _ in range(warm):
        once()
    lat = [once() for _ in range(iters)]
    window, pending = 8, []
    last = None
    deltas = []
    t0 = time.perf_counter()
    for _ in range(iters):
        pending.append(submit())
        if len(pending) >= window:
            ray_tpu.get(pending.pop(0), timeout=60)
            now = time.perf_counter()
            if last is not None:
                deltas.append(now - last)
            last = now
    for r in pending:
        ray_tpu.get(r, timeout=60)
    wall = time.perf_counter() - t0
    res = {f"serial_{k}": v for k, v in _percentiles_us(lat, hops).items()}
    piped = _percentiles_us(deltas, hops)
    res.update({
        "hops": hops,
        "per_hop_us_p50": piped["per_hop_us_p50"],
        "per_hop_us_p95": piped["per_hop_us_p95"],
        "pipelined_items_per_s": round(iters / wall, 1),
    })
    return res


def measure_dag(quick: bool = False) -> dict:
    """Compiled-graph microbench (SCALE_DAG=1): p50/p95 per-hop
    overhead of a 3-stage actor pipeline on compiled channels vs the
    legacy per-call task path — same-node, plus a 2-node loopback leg
    (skipped under SCALE_QUICK) whose cross-node edges ride the binary
    transfer plane."""
    import ray_tpu

    iters = 300 if quick else 2000
    warm = 20 if quick else 100

    @ray_tpu.remote
    class Stage:
        def step(self, x):
            return x

    out: dict = {"stages": 3, "iters": iters}
    ray_tpu.init(num_cpus=4)
    try:
        actors = [Stage.remote() for _ in range(3)]
        out["same_node"] = _measure_compiled_chain(ray_tpu, actors,
                                                   iters, warm)
        out["same_node_legacy"] = _measure_legacy_chain(
            ray_tpu, actors, iters, warm)
        out["speedup_p50"] = round(
            out["same_node_legacy"]["per_hop_us_p50"]
            / max(out["same_node"]["per_hop_us_p50"], 1e-9), 2)
        out["serial_speedup_p50"] = round(
            out["same_node_legacy"]["serial_per_hop_us_p50"]
            / max(out["same_node"]["serial_per_hop_us_p50"], 1e-9), 2)
    finally:
        ray_tpu.shutdown()
    if quick:
        return out

    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster()
    cluster.add_node(resources={"CPU": 2.0, "remote": 1.0})
    ray_tpu.init(num_cpus=2, gcs_address=cluster.gcs_address)
    try:
        cluster.wait_for_nodes(2)
        mid = Stage.options(resources={"remote": 1}).remote()
        actors = [Stage.remote(), mid, Stage.remote()]
        out["two_node"] = _measure_compiled_chain(
            ray_tpu, actors, max(iters // 4, 100), warm)
        out["two_node_legacy"] = _measure_legacy_chain(
            ray_tpu, actors, max(iters // 4, 100), warm)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    return out


def measure_sched(ray_tpu, quick: bool = False) -> dict:
    """Scheduler decision microbench (SCALE_SCHED=1): placement
    throughput draining no-op tasks over a 2-node cluster, plus the
    decision-latency histogram (submit -> terminal placement) and the
    outcome mix from the decision trace.  Latency percentiles come
    from the head node's ray_tpu_sched_placement_seconds aggregate
    (bucket-resolution); outcomes are cluster-merged."""
    from ray_tpu.util import state as state_api
    from ray_tpu.util.metrics import (SCHED_PLACEMENT_SECONDS_METRIC,
                                      hist_quantile)

    @ray_tpu.remote
    def noop(i):
        return i

    def _hist_snapshot() -> dict:
        agg = {"buckets": {}, "sum": 0.0, "count": 0}
        for s in ray_tpu._ensure_connected().metrics_scrape():
            if s.get("name") != SCHED_PLACEMENT_SECONDS_METRIC:
                continue
            for b, c in (s.get("buckets") or {}).items():
                agg["buckets"][b] = agg["buckets"].get(b, 0) + c
            agg["count"] += int(s.get("count") or 0)
            agg["sum"] += float(s.get("sum") or 0.0)
        return agg

    n = 100 if quick else 400
    ray_tpu.get([noop.remote(i) for i in range(8)])   # warm pools
    base = _hist_snapshot()
    t0 = time.perf_counter()
    ray_tpu.get([noop.remote(i) for i in range(n)])
    wall = time.perf_counter() - t0

    summary = state_api.summarize_scheduling()
    # Bench-window delta: warm-up placements wait on worker-pool
    # spawn (seconds) and would drown the steady-state percentiles.
    after = _hist_snapshot()
    merged = {
        "buckets": {b: c - base["buckets"].get(b, 0)
                    for b, c in after["buckets"].items()},
        "sum": after["sum"] - base["sum"],
        "count": after["count"] - base["count"],
    }
    return {
        "tasks": n,
        "placements_per_s": round(n / wall, 1),
        "decision_latency_ms_p50": round(
            hist_quantile(merged, 0.50) * 1000.0, 3),
        "decision_latency_ms_p95": round(
            hist_quantile(merged, 0.95) * 1000.0, 3),
        "decisions_recorded": summary["decisions"],
        "outcomes": summary["outcomes"],
    }


def run_envelope(node_counts: List[int], n_tasks: int, n_actors: int,
                 n_pgs: int, churn: int) -> dict:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    results = []
    for nodes in node_counts:
        cluster = Cluster()
        extra = nodes - 1
        for _ in range(extra):
            cluster.add_node(resources={"CPU": 2.0})
        ray_tpu.init(num_cpus=2, gcs_address=cluster.gcs_address)
        try:
            cluster.wait_for_nodes(nodes)
            row = {
                "nodes": nodes,
                "tasks_per_s": round(measure_tasks(ray_tpu, n_tasks), 1),
                "actors_per_s": round(
                    measure_actors(ray_tpu, n_actors), 1),
                **measure_pg(ray_tpu, n_pgs),
            }
            if nodes == node_counts[-1]:
                row["actor_churn_per_s"] = round(
                    measure_actor_churn(ray_tpu, churn), 1)
            results.append(row)
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
    return {
        "metric": "scale_envelope",
        "host_cpus": os.cpu_count(),
        "n_tasks": n_tasks, "n_actors": n_actors, "n_pgs": n_pgs,
        "churn_actors": churn,
        "levels": results,
        "reference": {"tasks_per_s_64node": 589,
                      "actors_per_s_64node": 580,
                      "pg_create_ms": 0.91, "pg_remove_ms": 0.86,
                      "source": "BASELINE.md (64x64-core cluster)"},
    }


def _merge_microbench(rnd: str, key: str, res: dict) -> None:
    path = f"MICROBENCH_{rnd}.json"
    blob = {}
    if os.path.exists(path):
        with open(path) as f:
            blob = json.load(f)
    blob[key] = res
    with open(path, "w") as f:
        json.dump(blob, f, indent=1)


def main() -> None:
    rnd = os.environ.get("SCALE_ROUND", "r07")
    quick = os.environ.get("SCALE_QUICK", "") not in ("", "0", "false")
    if os.environ.get("SCALE_SCHED", "") not in ("", "0", "false"):
        # Scheduler decision microbench: placements/s + decision
        # latency p50/p95 over a 2-node cluster, from the decision
        # trace this round introduced.
        import ray_tpu
        from ray_tpu.cluster_utils import Cluster
        cluster = Cluster()
        cluster.add_node(resources={"CPU": 2.0})
        ray_tpu.init(num_cpus=2, gcs_address=cluster.gcs_address)
        try:
            cluster.wait_for_nodes(2)
            res = measure_sched(ray_tpu, quick=quick)
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
        _merge_microbench(rnd, "sched", res)
        print(json.dumps({"metric": "sched", **res}))
        return
    if os.environ.get("SCALE_DAG", "") not in ("", "0", "false"):
        # Compiled-graph microbench: 3-stage actor pipeline, per-hop
        # overhead on compiled channels vs the legacy per-call path.
        # SCALE_QUICK shrinks iterations and skips the 2-node leg so
        # it runs in seconds locally.
        res = measure_dag(quick=quick)
        _merge_microbench(rnd, "dag", res)
        print(json.dumps({"metric": "dag", **res}))
        return
    if os.environ.get("SCALE_OBJECT_TRANSFER", "") not in ("", "0",
                                                           "false"):
        # Object-transfer microbench only: loopback two-node pull of a
        # 256 MiB object, stop-and-wait (window=1) vs windowed binary
        # stream.  Recorded into MICROBENCH_<round>.json next to the
        # single-node microbench numbers.
        size = int(os.environ.get("SCALE_TRANSFER_MB", "256"))
        res = measure_object_transfer(size)
        _merge_microbench(rnd, "object_transfer", res)
        print(json.dumps({"metric": "object_transfer", **res}))
        return
    if quick:
        out = run_envelope([1, 2], n_tasks=60, n_actors=8, n_pgs=5,
                           churn=20)
    else:
        out = run_envelope([1, 2, 4, 8], n_tasks=400, n_actors=40,
                           n_pgs=20, churn=200)
    with open(f"SCALE_{rnd}.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
