"""Serve benchmark: decode throughput + TTFT for continuous batching.

Analog of BASELINE.json config #5 ("Llama Ray Serve continuous
batching") scaled to the attached single chip: a GPT-2-small-class
model served through the ContinuousBatcher engine, closed-loop clients
firing short prompts.  Writes SERVE_BENCH_<round>.json (SERVE_ROUND
env, default r05) plus release_logs/last_good/, and prints one JSON
line.  Backend init goes through ray_tpu.util.hwprobe (subprocess
probe + bounded retries) so a wedged tunnel yields a structured
stale record instead of rc=1.  The reference publishes no serving numbers (BASELINE.md
"published": {}), so the recorded numbers ARE the baseline this repo
must beat in later rounds.

History: r02 920 tok/s (sync loop); r03 recorded 4,351 tok/s from a
pre-pipelined engine (the shipped engine measured 4.6-4.7k in tuning).
Round-4 target: >= 5,000 decode tok/s with TTFT p50 <= 50 ms.  The
measured dispatch ceiling on this tunnel was ~6.1k at chunk 16, so the
default config is chunk 16 / depth 4; env knobs let the driver sweep:

  SERVE_SLOTS / SERVE_CHUNK / SERVE_DEPTH / SERVE_MAX_NEW — one run
  SERVE_SWEEP=1 — try several (chunk, depth) points with a short run
                  each, then measure the best at full length
  SERVE_MODEL=llama-1b — the ~1B-param serving config
"""

from __future__ import annotations

import json
import os
import threading
import time


def _build(cfg_name: str):
    import jax
    from ray_tpu.models import transformer
    if cfg_name == "llama-8b-int8":
        # The BASELINE north star: 8B-shape Llama serving on ONE 16 GB
        # chip.  bf16 weights alone are ~15 GB (no room for KV); the
        # weight-only int8 path (models/quantize.py) is ~7.5 GB + KV.
        # Weights are random int8 built directly on device — identical
        # compute/memory profile to a converted real checkpoint.
        from ray_tpu.models import quantize
        cfg = transformer.TransformerConfig(
            vocab_size=128_256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14_336, max_seq=1024,
            dtype=jax.numpy.bfloat16, remat=False)
        params = quantize.init_quantized_params(cfg, jax.random.PRNGKey(0))
        return cfg, params, "llama-8b-class int8 (~8B)"
    if cfg_name == "llama-1b":
        cfg = transformer.TransformerConfig(
            vocab_size=32_000, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=8, d_ff=5504, max_seq=1024,
            dtype=jax.numpy.bfloat16, remat=False)
        label = "llama-1b-class (~1.1B)"
    else:
        cfg = transformer.TransformerConfig(
            vocab_size=50_304, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq=1024, arch="gpt2",
            dtype=jax.numpy.bfloat16, remat=False)
        label = "gpt2-small (124M)"
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params, label


def _run_once(cfg, params, *, num_slots, decode_chunk, pipeline_depth,
              max_new, n_requests, max_len=256, prompt_pad=64):
    import numpy as np
    from ray_tpu.serve.llm import ContinuousBatcher

    bat = ContinuousBatcher(params, cfg, num_slots=num_slots,
                            max_len=max_len, prompt_pad=prompt_pad,
                            decode_chunk=decode_chunk,
                            pipeline_depth=pipeline_depth)
    try:
        return _measure(bat, cfg, num_slots=num_slots,
                        decode_chunk=decode_chunk,
                        pipeline_depth=pipeline_depth,
                        max_new=max_new, n_requests=n_requests)
    finally:
        bat.stop()


def _measure(bat, cfg, *, num_slots, decode_chunk, pipeline_depth,
             max_new, n_requests):
    """Two phases against one engine config.

    Throughput: open-loop saturation — ALL requests submitted up front
    (the engine admits as slots free), one waiter thread.  The previous
    closed-loop one-thread-per-slot harness put num_slots Python
    threads on this 1-vCPU host; at 48 slots the GIL thrash measured
    the harness, not the engine.  TTFT under saturation is queueing
    delay, so it is measured separately.

    Latency: 4 closed-loop clients (light load, slots mostly free) —
    the TTFT a user sees when the service is not saturated.
    """
    import numpy as np
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, size=(16,)).tolist()
               for _ in range(n_requests)]
    bat.generate(prompts[0], max_new=4)       # compile warmup

    t0 = time.time()
    reqs = [bat.submit(p, max_new=max_new) for p in prompts]
    for r in reqs:
        if not r.done.wait(600):
            raise TimeoutError("saturated run stalled")
        if r.error is not None:
            raise r.error
    wall = time.time() - t0
    total_tokens = sum(len(r.tokens) for r in reqs)

    lat_results = []
    lock = threading.Lock()
    # 96 samples: enough that the reported p95 is a real percentile
    # (index 91), not the max of a handful of requests.
    lat_work = list(prompts[:96])

    def client():
        while True:
            with lock:
                if not lat_work:
                    return
                p = lat_work.pop()
            out = bat.generate(p, max_new=max_new, timeout=600)
            with lock:
                lat_results.append(out)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Streaming check: time-to-first-token through the stream path.
    st0 = time.time()
    first_tok_s = None
    streamed = []
    for tok in bat.generate_stream(prompts[0], max_new=8):
        if first_tok_s is None:
            first_tok_s = time.time() - st0
        streamed.append(tok)

    from ray_tpu.util.state import _percentile as pct

    ttfts = sorted(r["ttft_s"] for r in lat_results)
    queues = sorted(r.get("queue_s", 0.0) for r in lat_results)
    prefills = sorted(r.get("prefill_s", 0.0) for r in lat_results)
    return {
        "num_slots": num_slots,
        "decode_chunk": decode_chunk,
        "pipeline_depth": pipeline_depth,
        "requests": len(reqs),
        "max_new_tokens": max_new,
        "req_per_s": round(len(reqs) / wall, 2),
        "decode_tokens_per_s": round(total_tokens / wall, 1),
        "ttft_p50_ms": round(pct(ttfts, 0.50) * 1e3, 1),
        "ttft_p95_ms": round(pct(ttfts, 0.95) * 1e3, 1),
        # Where the TTFT milliseconds go (engine-side decomposition:
        # queue = submit -> slot admission, prefill = admission ->
        # first token; route is the proxy/router hop, not traversed by
        # this direct-engine harness) — so a regression in a future
        # round is attributable to a stage, not just a total.
        "ttft_breakdown": {
            "queue_p50_ms": round(pct(queues, 0.50) * 1e3, 1),
            "queue_p95_ms": round(pct(queues, 0.95) * 1e3, 1),
            "prefill_p50_ms": round(pct(prefills, 0.50) * 1e3, 1),
            "prefill_p95_ms": round(pct(prefills, 0.95) * 1e3, 1),
            "route": "n/a (direct engine, no proxy hop)",
        },
        "ttft_load": "4 closed-loop clients (unsaturated), 96 samples",
        "stream_first_token_ms": round((first_tok_s or 0) * 1e3, 1),
        "stream_tokens": len(streamed),
        "wall_s": round(wall, 2),
    }


def _pct(sorted_vals, q):
    from ray_tpu.util.state import _percentile
    return _percentile(sorted_vals, q)


def _shared_prefix_workload(cfg, n_requests, n_lat, *, sys_len,
                            tail_len, block_size, seed=0):
    """The millions-of-users shape (ROADMAP open item 1): 80% of
    requests are one of 4 long system prompts + a tiny unique tail,
    20% are fully unique.  sys_len is block-aligned so the whole
    system prompt is prefix-shareable.  Returns (throughput_prompts,
    latency_prompts) drawn from the SAME system prompts, so the
    latency phase runs against a warm cache."""
    import numpy as np
    rng = np.random.RandomState(seed)
    sys_len = (sys_len // block_size) * block_size
    sys_prompts = [rng.randint(0, cfg.vocab_size,
                               size=(sys_len,)).tolist()
                   for _ in range(4)]

    def draw():
        if rng.random() < 0.8:
            return sys_prompts[rng.randint(4)] + rng.randint(
                0, cfg.vocab_size, size=(tail_len,)).tolist()
        return rng.randint(0, cfg.vocab_size,
                           size=(sys_len + tail_len,)).tolist()

    return ([draw() for _ in range(n_requests)],
            [draw() for _ in range(n_lat)])


def _ttft_split(results):
    hits = sorted(r["ttft_s"] for r in results if r["cache_hit"])
    misses = sorted(r["ttft_s"] for r in results if not r["cache_hit"])
    cell = lambda xs: {  # noqa: E731
        "n": len(xs),
        "p50_ms": round(_pct(xs, 0.50) * 1e3, 1) if xs else None,
        "p95_ms": round(_pct(xs, 0.95) * 1e3, 1) if xs else None}
    return {"hit": cell(hits), "miss": cell(misses)}


def _measure_shared_prefix(bat, prompts, lat_prompts, max_new,
                           n_clients):
    """Two phases over the shared-prefix workload.

    Throughput: open-loop saturation — all requests submitted up front
    (the >= 48-concurrent-clients shape without 48 Python threads on a
    1-vCPU host).  TTFT under saturation is queue-position, so it is
    NOT reported from this phase.

    Latency: n_clients closed-loop clients against the now-warm prefix
    cache — the TTFT a user actually sees, split by cache_hit (this is
    where a hit's suffix-only narrow prefill shows up).  On CPU one
    client keeps the serial host from charging concurrent decode
    compute to TTFT; on TPU extra decode width is near-free, so 4."""
    bat.generate(prompts[0][:8], max_new=2)   # compile warmup
    t0 = time.time()
    reqs = [bat.submit(p, max_new=max_new) for p in prompts]
    for r in reqs:
        if not r.done.wait(600):
            raise TimeoutError("shared_prefix run stalled")
        if r.error is not None:
            raise r.error
    wall = time.time() - t0
    total_tokens = sum(len(r.tokens) for r in reqs)

    lat_results = []
    lock = threading.Lock()
    work = list(lat_prompts)

    def client():
        while True:
            with lock:
                if not work:
                    return
                p = work.pop()
            out = bat.generate(p, max_new=max_new, timeout=600)
            with lock:
                lat_results.append(out)

    threads = [threading.Thread(target=client)
               for _ in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    out = {
        "requests": len(reqs),
        "wall_s": round(wall, 2),
        "decode_tokens_per_s": round(total_tokens / wall, 1),
        "ttft_load": f"{n_clients} closed-loop clients (unsaturated), "
                     f"{len(lat_results)} samples, warm cache",
        "ttft_p50_ms": round(_pct(sorted(
            r["ttft_s"] for r in lat_results), 0.50) * 1e3, 1),
        "ttft_p95_ms": round(_pct(sorted(
            r["ttft_s"] for r in lat_results), 0.95) * 1e3, 1),
        "ttft_by_cache_hit": _ttft_split(lat_results),
        "finish_reasons": {
            fr: sum(1 for r in reqs if r.finish_reason == fr)
            for fr in sorted({r.finish_reason for r in reqs})},
    }
    stats = getattr(bat, "kv_stats", None)
    if stats is not None:
        st = stats()
        pc = st["prefix_cache"]
        out["prefix_cache"] = {
            "hit_ratio": round(pc["hits"] / max(pc["queries"], 1), 3),
            "queries": pc["queries"],
            "hits": pc["hits"],
            "hit_tokens": pc["hit_tokens"],
            "evictions": pc["evictions"],
            "cached_blocks": pc["cached_blocks"],
        }
        out["kv_blocks"] = st["blocks"]
    return out


def _run_shared_prefix(cfg, params, label, dev, on_tpu) -> dict:
    """Paged vs dense at KV-MEMORY PARITY: the dense engine provisions
    max_len positions per slot, so a fixed HBM budget caps its slot
    count; the paged engine spends the SAME budget as a block pool and
    runs more slots because requests only hold blocks for tokens they
    actually have (and 80% of them SHARE their system-prompt blocks).
    The win measured here is the paged-KV thesis: more concurrency and
    earlier admission per byte of KV, not a faster kernel."""
    from ray_tpu.serve.llm import ContinuousBatcher, PagedBatcher

    block = 16
    if on_tpu:
        # max_len must cover prompt (192+8) + max_new (64) = 264 with
        # one cap-margin position to spare, or every request truncates
        # with finish_reason "cache" and the tok/s compare is bogus.
        dense_slots, paged_slots, max_len = 16, 48, 288
        chunk, depth, max_new, n_requests = 16, 3, 64, 256
        prompt_pad, sys_len, tail_len = 224, 192, 8
    else:
        dense_slots, paged_slots, max_len = 4, 8, 128
        chunk, depth, max_new, n_requests = 4, 2, 16, 48
        prompt_pad, sys_len, tail_len = 64, 48, 4
    kv_budget_blocks = dense_slots * (max_len // block)
    n_clients = 4 if on_tpu else 1
    n_lat = 96 if on_tpu else 24
    prompts, lat_prompts = _shared_prefix_workload(
        cfg, n_requests, n_lat, sys_len=sys_len, tail_len=tail_len,
        block_size=block)
    engines = {}
    dense = ContinuousBatcher(params, cfg, num_slots=dense_slots,
                              max_len=max_len, prompt_pad=prompt_pad,
                              decode_chunk=chunk, pipeline_depth=depth)
    try:
        engines["dense"] = {
            "num_slots": dense_slots, "max_len": max_len,
            "kv_positions": dense_slots * max_len,
            **_measure_shared_prefix(dense, prompts, lat_prompts,
                                     max_new, n_clients)}
    finally:
        dense.stop()
    paged = PagedBatcher(params, cfg, num_slots=paged_slots,
                         max_len=max_len, prompt_pad=prompt_pad,
                         decode_chunk=chunk, pipeline_depth=depth,
                         kv_block_size=block,
                         kv_num_blocks=kv_budget_blocks)
    try:
        engines["paged"] = {
            "num_slots": paged_slots, "max_len": max_len,
            "kv_block_size": block, "kv_num_blocks": kv_budget_blocks,
            "kv_positions": kv_budget_blocks * block,
            **_measure_shared_prefix(paged, prompts, lat_prompts,
                                     max_new, n_clients)}
    finally:
        paged.stop()
    d, p = engines["dense"], engines["paged"]
    hit_p50 = p["ttft_by_cache_hit"]["hit"]["p50_ms"]
    return {
        "metric": "serve_shared_prefix",
        "scenario": "shared_prefix (80% of requests share one of 4 "
                    "long system prompts)",
        "model": label,
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "platform": "tpu" if on_tpu else "cpu",
        "kv_budget_note": "both engines hold the same KV positions; "
                          "dense spends them as per-slot max_len "
                          "slabs, paged as a shared block pool",
        "engines": engines,
        "paged_vs_dense": {
            "decode_tps_speedup": round(
                p["decode_tokens_per_s"]
                / max(d["decode_tokens_per_s"], 1e-9), 2),
            "ttft_p50_cache_hit_vs_dense": (
                round(hit_p50 / max(d["ttft_p50_ms"], 1e-9), 3)
                if hit_p50 is not None else None),
        },
    }


# ===========================================================================
# Bursty diurnal replay: autoscaling + admission control + chaos
# ===========================================================================
def _run_bursty() -> dict:
    """Diurnal-replay drill for the overload-robustness layer
    (ROADMAP item 5 acceptance): a low -> burst -> low client pattern
    against an autoscaled, admission-controlled deployment.

    Asserts-by-measurement: TTFT p95 stays inside the configured SLO
    while the replica count tracks load (scale_up AND scale_down
    events in the capture); excess burst traffic is shed with
    structured rejections whose p95 latency is < 10 ms; a seeded
    chaos kill_replica during the downscale phase produces zero
    user-visible errors.  Pure control-plane behavior — runs the same
    on CPU and TPU (platform recorded in the JSON)."""
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import config
    from ray_tpu.serve._admission import RequestRejectedError
    from ray_tpu.serve._controller import CONTROLLER_NAME
    from ray_tpu.util import chaos as chaos_api
    from ray_tpu.util import metrics
    from ray_tpu.util.state import _percentile as pct

    TTFT_SLO_MS = 400.0
    ray_tpu.init(num_cpus=8)

    @serve.deployment(
        num_replicas=1, max_concurrent_queries=16,
        autoscaling_config={"min_replicas": 1, "max_replicas": 4,
                            "target_queue_depth": 2.0,
                            "target_ttft_ms": TTFT_SLO_MS,
                            "downscale_slo_fraction": 0.9,
                            "upscale_delay_s": 0.3,
                            "downscale_delay_s": 2.0,
                            "interval_s": 0.25},
        admission_config={"max_queue_depth": 12,
                          "retry_after_s": 0.2})
    class Diurnal:
        async def __call__(self, x):
            import asyncio
            await asyncio.sleep(0.04)
            return x

    handle = serve.run(Diurnal.bind())

    samples = []                  # (t, running, draining, target)
    stop_sampler = threading.Event()

    def sampler():
        t0 = time.time()
        while not stop_sampler.is_set():
            try:
                st = serve.status()["Diurnal"]
                samples.append((round(time.time() - t0, 2),
                                len(st["replica_states"]),
                                st["draining_replicas"],
                                st["target_replicas"]))
            except Exception:
                pass
            stop_sampler.wait(0.25)

    threading.Thread(target=sampler, daemon=True).start()

    lock = threading.Lock()
    phase_stats: dict = {}

    def run_phase(name: str, seconds: float, clients: int) -> None:
        oks, rejects, errors = [], [], []
        deadline = time.time() + seconds

        def client():
            while time.time() < deadline:
                t0 = time.perf_counter()
                try:
                    ray_tpu.get(handle.remote(1), timeout=30)
                    dt = time.perf_counter() - t0
                    with lock:
                        oks.append(dt)
                except RequestRejectedError as e:
                    dt = time.perf_counter() - t0
                    with lock:
                        rejects.append((dt, e.reason,
                                        e.retry_after_s))
                    time.sleep(min(e.retry_after_s, 0.3))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))

        threads = [threading.Thread(target=client)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ok_sorted = sorted(oks)
        rej_sorted = sorted(r[0] for r in rejects)
        phase_stats[name] = {
            "seconds": seconds, "clients": clients,
            "completed": len(oks), "shed": len(rejects),
            "errors": len(errors), "error_samples": errors[:3],
            "ttft_p50_ms": (round(pct(ok_sorted, 0.5) * 1e3, 1)
                            if oks else None),
            "ttft_p95_ms": (round(pct(ok_sorted, 0.95) * 1e3, 1)
                            if oks else None),
            "reject_p95_ms": (round(pct(rej_sorted, 0.95) * 1e3, 3)
                              if rejects else None),
            "reject_reasons": sorted({r[1] for r in rejects}),
        }

    run_phase("low_warm", 6.0, 2)
    run_phase("burst", 10.0, 16)
    # Downscale phase: arm ONE seeded replica kill so the drill
    # covers chaos-during-scale-down (zero user-visible errors —
    # un-started requests fail over).
    config.set("chaos_seed", 17)
    config.set("chaos_spec", "serve.assign:kind=kill_replica:p=1:n=1")
    chaos_api.refresh()
    chaos_api.reset_trace()
    run_phase("low_cooldown", 14.0, 2)
    chaos_trace = [(s, site, kind)
                   for s, site, kind in chaos_api.trace()]
    config.set("chaos_spec", "")
    config.set("chaos_seed", 0)
    chaos_api.refresh()
    stop_sampler.set()

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ostat = ray_tpu.get(controller.overload_status.remote(),
                        timeout=30)["Diurnal"]
    shed_counts: dict = {}
    for s in metrics.scrape():
        if s["name"] == metrics.SERVE_REQUESTS_SHED_METRIC:
            shed_counts[(s["tags"] or {}).get("reason", "?")] = \
                s["value"]
    events = ostat.get("autoscale_events") or []
    actions = [e.get("action") for e in events]
    max_replicas = max((s[1] for s in samples), default=1)
    out = {
        "metric": "serve_bursty_diurnal",
        "scenario": "bursty diurnal replay: low -> burst -> low "
                    "against SLO autoscaling + admission control, "
                    "seeded kill_replica during the downscale",
        "ttft_slo_ms": TTFT_SLO_MS,
        "phases": phase_stats,
        "replica_timeline": samples,
        "max_replicas_seen": max_replicas,
        "scale_up_seen": "scale_up" in actions,
        "scale_down_seen": "scale_down" in actions,
        "autoscale_events": events,
        "shed_total_by_reason": shed_counts,
        "chaos_trace": chaos_trace,
        "chaos_user_visible_errors": sum(
            p["errors"] for p in phase_stats.values()),
        "slo_met": all(
            p["ttft_p95_ms"] is not None
            and p["ttft_p95_ms"] <= TTFT_SLO_MS
            for p in phase_stats.values()),
    }
    serve.shutdown()
    ray_tpu.shutdown()
    return out


def main() -> None:
    """Retry-once wrapper: a tunnel that probes healthy can still wedge
    between the probe and first device use (the round-3/4 evidence-loss
    mode: capture died rc=1 mid-run).  jax caches a failed backend for
    the life of the process, so the retry re-execs a FRESH process; a
    second failure emits the structured last-good/stale record and
    exits 0 — the driver always gets one JSON line."""
    import sys as _sys
    import traceback as _tb
    try:
        _run()
        return
    except (SystemExit, KeyboardInterrupt):
        raise
    except BaseException:
        _tb.print_exc()
    from ray_tpu.util import hwprobe
    model = os.environ.get("SERVE_MODEL", "gpt2s")
    name = hwprobe.lg_name("SERVE_BENCH", model, "gpt2s")
    if not os.environ.get("SERVE_BENCH_RETRIED"):
        print("serve_bench: run failed; retrying once in a fresh "
              "process", file=_sys.stderr, flush=True)
        os.environ["SERVE_BENCH_RETRIED"] = "1"
        os.execv(_sys.executable,
                 [_sys.executable, os.path.abspath(__file__)])
    print(json.dumps(hwprobe.stale_record(
        name, {"error": "serve bench crashed twice (see stderr)"},
        "fresh serve capture failed twice; emitting last-good")))


def _run() -> None:
    from ray_tpu.util import hwprobe

    model = os.environ.get("SERVE_MODEL", "gpt2s")
    lg_name = hwprobe.lg_name("SERVE_BENCH", model, "gpt2s")

    if os.environ.get("SERVE_SCENARIO") == "bursty":
        # Control-plane drill: no model, no device — runs identically
        # with or without a chip, so it records unconditionally under
        # its OWN last-good key (never the default serve-bench record:
        # the payload shapes differ — the PR-9 clobbering bug class).
        try:
            import jax
            platform = jax.devices()[0].platform
        except Exception:
            platform = "unknown"
        out = _run_bursty()
        out["platform"] = platform
        rnd = os.environ.get("SERVE_ROUND", "r08")
        with open(f"SERVE_BENCH_{rnd}_bursty.json", "w") as f:
            json.dump(out, f, indent=1)
        hwprobe.record_last_good(
            hwprobe.lg_name("SERVE_BENCH_BURSTY", model, "gpt2s"),
            out)
        print(json.dumps(out))
        return

    # Probe in a subprocess before importing jax (see bench.py: two
    # rounds of driver captures died on a wedged tunnel at import).
    hwprobe.ensure_backend(
        lg_name, "fresh serve capture failed: TPU tunnel never initialized")

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    cfg, params, label = _build(model)

    if os.environ.get("SERVE_SCENARIO") == "shared_prefix":
        out = _run_shared_prefix(cfg, params, label, dev, on_tpu)
        rnd = os.environ.get("SERVE_ROUND", "r07")
        # Platform is recorded IN the JSON, so a CPU capture is a
        # legitimate record for this scenario (paged-vs-dense at
        # memory parity is an engine property, not a device one).
        with open(f"SERVE_BENCH_{rnd}.json", "w") as f:
            json.dump(out, f, indent=1)
        if on_tpu:
            # Own last-good key: this record is shaped {engines: ...},
            # not the default serve-bench payload — writing it under
            # lg_name would clobber the default scenario's regression
            # record (and get emitted as its stale fallback).
            hwprobe.record_last_good(
                hwprobe.lg_name("SERVE_BENCH_SHARED_PREFIX", model,
                                "gpt2s"), out)
        print(json.dumps(out))
        return

    slots = int(os.environ.get("SERVE_SLOTS", 16 if on_tpu else 4))
    chunk = int(os.environ.get("SERVE_CHUNK", 16 if on_tpu else 4))
    depth = int(os.environ.get("SERVE_DEPTH", 4 if on_tpu else 2))
    max_new = int(os.environ.get("SERVE_MAX_NEW",
                                 64 if on_tpu else 8))
    n_requests = 256 if on_tpu else 12

    sweep_on = os.environ.get("SERVE_SWEEP", "").lower() \
        not in ("", "0", "false")
    if sweep_on and on_tpu:
        # Short runs over the grid, then the winner at full length.
        # Slots dominate: tokens/dispatch = slots x chunk and the
        # per-dispatch cost through the tunneled chip is mostly fixed
        # (~30-60 ms), so wider decode batches win until device time
        # passes the link latency (measured: raw piped ceiling 8.2k
        # tok/s at 48x16, falling again by 64x16).
        best, best_cfg = -1.0, None
        grid = [(16, 16, 3), (32, 16, 3), (48, 8, 3), (48, 16, 3),
                (48, 16, 2)]
        sweep_log = []
        for s, c, d in grid:
            r = _run_once(cfg, params, num_slots=s,
                          decode_chunk=c, pipeline_depth=d,
                          max_new=max_new, n_requests=96)
            sweep_log.append({"slots": s, "chunk": c, "depth": d,
                              "tps": r["decode_tokens_per_s"],
                              "ttft_p50_ms": r["ttft_p50_ms"]})
            # Round target: TTFT p50 <= 50 ms at light load.
            if r["decode_tokens_per_s"] > best \
                    and r["ttft_p50_ms"] <= 50.0:
                best, best_cfg = r["decode_tokens_per_s"], (s, c, d)
        if best_cfg is None:                    # nothing met the TTFT bar
            e = max(sweep_log, key=lambda e: e["tps"])
            best_cfg = (e["slots"], e["chunk"], e["depth"])
        slots, chunk, depth = best_cfg
    else:
        sweep_log = None

    r = _run_once(cfg, params, num_slots=slots, decode_chunk=chunk,
                  pipeline_depth=depth, max_new=max_new,
                  n_requests=n_requests)
    out = {
        "metric": "serve_continuous_batching",
        "model": label,
        "device": str(getattr(dev, "device_kind", dev.platform)),
        **r,
        "vs_r02_decode_tps": round(
            r["decode_tokens_per_s"] / 920.0, 2),
    }
    if sweep_log:
        out["sweep"] = sweep_log
    suffix = "" if model == "gpt2s" else f"_{model.replace('-', '_')}"
    rnd = os.environ.get("SERVE_ROUND", "r05")
    if on_tpu:   # never clobber the hardware record with a CPU smoke run
        with open(f"SERVE_BENCH_{rnd}{suffix}.json", "w") as f:
            json.dump(out, f, indent=1)
        hwprobe.record_last_good(lg_name, out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
