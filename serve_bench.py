"""Serve benchmark: decode throughput + TTFT for continuous batching.

Analog of BASELINE.json config #5 ("Llama Ray Serve continuous
batching") scaled to the attached single chip: a GPT-2-small-class
model served through the ContinuousBatcher engine, closed-loop clients
firing short prompts.  Writes SERVE_BENCH_r03.json and prints one JSON
line.  The reference publishes no serving numbers (BASELINE.md
"published": {}), so the recorded numbers ARE the baseline this repo
must beat in later rounds.

Round-2 numbers (SERVE_BENCH_r02.json, the bar to beat): 920 decode
tok/s aggregate, 28.8 req/s, TTFT p50 172 ms / p99 239 ms.  Round-3
targets (VERDICT): >= 5000 decode tok/s, TTFT p50 < 50 ms,
p99 < 150 ms — reached by the pipelined engine (in-flight dispatches +
async device->host token copies, serve/llm.py).
"""

from __future__ import annotations

import json
import threading
import time


def main() -> None:
    import numpy as np
    import jax
    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import ContinuousBatcher

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    cfg = transformer.TransformerConfig(
        vocab_size=50_304, d_model=768, n_layers=12, n_heads=12,
        d_ff=3072, max_seq=1024, arch="gpt2",
        dtype=jax.numpy.bfloat16, remat=False)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    num_slots = 16 if on_tpu else 4
    max_new = 64 if on_tpu else 8
    n_requests = 256 if on_tpu else 12
    bat = ContinuousBatcher(params, cfg, num_slots=num_slots,
                            max_len=256, prompt_pad=64,
                            decode_chunk=8 if on_tpu else 4,
                            pipeline_depth=3 if on_tpu else 2)

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, size=(16,)).tolist()
               for _ in range(n_requests)]

    # Warmup: compile prefill + decode paths.
    bat.generate(prompts[0], max_new=4)

    # Closed loop at concurrency == num_slots: every slot stays busy but
    # requests don't pile up in the admission queue (queue wait would
    # dominate TTFT and measure the backlog, not the system).
    results = []
    lock = threading.Lock()
    work = list(prompts)

    def client():
        while True:
            with lock:
                if not work:
                    return
                p = work.pop()
            out = bat.generate(p, max_new=max_new, timeout=600)
            with lock:
                results.append(out)

    t0 = time.time()
    threads = [threading.Thread(target=client)
               for _ in range(num_slots)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0

    # Streaming check: time-to-first-token through the stream path.
    st0 = time.time()
    stream_iter = bat.generate_stream(prompts[0], max_new=8)
    first_tok_s = None
    streamed = []
    for tok in stream_iter:
        if first_tok_s is None:
            first_tok_s = time.time() - st0
        streamed.append(tok)
    bat.stop()

    ttfts = sorted(r["ttft_s"] for r in results)
    total_tokens = sum(len(r["tokens"]) for r in results)
    out = {
        "metric": "serve_continuous_batching",
        "model": "gpt2-small (124M)",
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "num_slots": num_slots,
        "requests": len(results),
        "max_new_tokens": max_new,
        "req_per_s": round(len(results) / wall, 2),
        "decode_tokens_per_s": round(total_tokens / wall, 1),
        "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1),
        "ttft_p99_ms": round(ttfts[int(len(ttfts) * 0.99)] * 1e3, 1),
        "stream_first_token_ms": round((first_tok_s or 0) * 1e3, 1),
        "stream_tokens": len(streamed),
        "wall_s": round(wall, 2),
        "vs_r02_decode_tps": round(total_tokens / wall / 920.0, 2),
    }
    with open("SERVE_BENCH_r03.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
