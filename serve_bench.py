"""Serve benchmark: decode throughput + TTFT for continuous batching.

Analog of BASELINE.json config #5 ("Llama Ray Serve continuous
batching") scaled to the attached single chip: a GPT-2-small-class
model served through the ContinuousBatcher engine, closed-loop clients
firing short prompts.  Writes SERVE_BENCH_<round>.json (SERVE_ROUND
env, default r05) plus release_logs/last_good/, and prints one JSON
line.  Backend init goes through ray_tpu.util.hwprobe (subprocess
probe + bounded retries) so a wedged tunnel yields a structured
stale record instead of rc=1.  The reference publishes no serving numbers (BASELINE.md
"published": {}), so the recorded numbers ARE the baseline this repo
must beat in later rounds.

History: r02 920 tok/s (sync loop); r03 recorded 4,351 tok/s from a
pre-pipelined engine (the shipped engine measured 4.6-4.7k in tuning).
Round-4 target: >= 5,000 decode tok/s with TTFT p50 <= 50 ms.  The
measured dispatch ceiling on this tunnel was ~6.1k at chunk 16, so the
default config is chunk 16 / depth 4; env knobs let the driver sweep:

  SERVE_SLOTS / SERVE_CHUNK / SERVE_DEPTH / SERVE_MAX_NEW — one run
  SERVE_SWEEP=1 — try several (chunk, depth) points with a short run
                  each, then measure the best at full length
  SERVE_MODEL=llama-1b — the ~1B-param serving config
"""

from __future__ import annotations

import json
import os
import threading
import time


def _build(cfg_name: str):
    import jax
    from ray_tpu.models import transformer
    if cfg_name == "llama-8b-int8":
        # The BASELINE north star: 8B-shape Llama serving on ONE 16 GB
        # chip.  bf16 weights alone are ~15 GB (no room for KV); the
        # weight-only int8 path (models/quantize.py) is ~7.5 GB + KV.
        # Weights are random int8 built directly on device — identical
        # compute/memory profile to a converted real checkpoint.
        from ray_tpu.models import quantize
        cfg = transformer.TransformerConfig(
            vocab_size=128_256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14_336, max_seq=1024,
            dtype=jax.numpy.bfloat16, remat=False)
        params = quantize.init_quantized_params(cfg, jax.random.PRNGKey(0))
        return cfg, params, "llama-8b-class int8 (~8B)"
    if cfg_name == "llama-1b":
        cfg = transformer.TransformerConfig(
            vocab_size=32_000, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=8, d_ff=5504, max_seq=1024,
            dtype=jax.numpy.bfloat16, remat=False)
        label = "llama-1b-class (~1.1B)"
    else:
        cfg = transformer.TransformerConfig(
            vocab_size=50_304, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq=1024, arch="gpt2",
            dtype=jax.numpy.bfloat16, remat=False)
        label = "gpt2-small (124M)"
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params, label


def _run_once(cfg, params, *, num_slots, decode_chunk, pipeline_depth,
              max_new, n_requests, max_len=256, prompt_pad=64):
    import numpy as np
    from ray_tpu.serve.llm import ContinuousBatcher

    bat = ContinuousBatcher(params, cfg, num_slots=num_slots,
                            max_len=max_len, prompt_pad=prompt_pad,
                            decode_chunk=decode_chunk,
                            pipeline_depth=pipeline_depth)
    try:
        return _measure(bat, cfg, num_slots=num_slots,
                        decode_chunk=decode_chunk,
                        pipeline_depth=pipeline_depth,
                        max_new=max_new, n_requests=n_requests)
    finally:
        bat.stop()


def _measure(bat, cfg, *, num_slots, decode_chunk, pipeline_depth,
             max_new, n_requests):
    """Two phases against one engine config.

    Throughput: open-loop saturation — ALL requests submitted up front
    (the engine admits as slots free), one waiter thread.  The previous
    closed-loop one-thread-per-slot harness put num_slots Python
    threads on this 1-vCPU host; at 48 slots the GIL thrash measured
    the harness, not the engine.  TTFT under saturation is queueing
    delay, so it is measured separately.

    Latency: 4 closed-loop clients (light load, slots mostly free) —
    the TTFT a user sees when the service is not saturated.
    """
    import numpy as np
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, size=(16,)).tolist()
               for _ in range(n_requests)]
    bat.generate(prompts[0], max_new=4)       # compile warmup

    t0 = time.time()
    reqs = [bat.submit(p, max_new=max_new) for p in prompts]
    for r in reqs:
        if not r.done.wait(600):
            raise TimeoutError("saturated run stalled")
        if r.error is not None:
            raise r.error
    wall = time.time() - t0
    total_tokens = sum(len(r.tokens) for r in reqs)

    lat_results = []
    lock = threading.Lock()
    # 96 samples: enough that the reported p95 is a real percentile
    # (index 91), not the max of a handful of requests.
    lat_work = list(prompts[:96])

    def client():
        while True:
            with lock:
                if not lat_work:
                    return
                p = lat_work.pop()
            out = bat.generate(p, max_new=max_new, timeout=600)
            with lock:
                lat_results.append(out)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Streaming check: time-to-first-token through the stream path.
    st0 = time.time()
    first_tok_s = None
    streamed = []
    for tok in bat.generate_stream(prompts[0], max_new=8):
        if first_tok_s is None:
            first_tok_s = time.time() - st0
        streamed.append(tok)

    from ray_tpu.util.state import _percentile as pct

    ttfts = sorted(r["ttft_s"] for r in lat_results)
    queues = sorted(r.get("queue_s", 0.0) for r in lat_results)
    prefills = sorted(r.get("prefill_s", 0.0) for r in lat_results)
    return {
        "num_slots": num_slots,
        "decode_chunk": decode_chunk,
        "pipeline_depth": pipeline_depth,
        "requests": len(reqs),
        "max_new_tokens": max_new,
        "req_per_s": round(len(reqs) / wall, 2),
        "decode_tokens_per_s": round(total_tokens / wall, 1),
        "ttft_p50_ms": round(pct(ttfts, 0.50) * 1e3, 1),
        "ttft_p95_ms": round(pct(ttfts, 0.95) * 1e3, 1),
        # Where the TTFT milliseconds go (engine-side decomposition:
        # queue = submit -> slot admission, prefill = admission ->
        # first token; route is the proxy/router hop, not traversed by
        # this direct-engine harness) — so a regression in a future
        # round is attributable to a stage, not just a total.
        "ttft_breakdown": {
            "queue_p50_ms": round(pct(queues, 0.50) * 1e3, 1),
            "queue_p95_ms": round(pct(queues, 0.95) * 1e3, 1),
            "prefill_p50_ms": round(pct(prefills, 0.50) * 1e3, 1),
            "prefill_p95_ms": round(pct(prefills, 0.95) * 1e3, 1),
            "route": "n/a (direct engine, no proxy hop)",
        },
        "ttft_load": "4 closed-loop clients (unsaturated), 96 samples",
        "stream_first_token_ms": round((first_tok_s or 0) * 1e3, 1),
        "stream_tokens": len(streamed),
        "wall_s": round(wall, 2),
    }


def main() -> None:
    """Retry-once wrapper: a tunnel that probes healthy can still wedge
    between the probe and first device use (the round-3/4 evidence-loss
    mode: capture died rc=1 mid-run).  jax caches a failed backend for
    the life of the process, so the retry re-execs a FRESH process; a
    second failure emits the structured last-good/stale record and
    exits 0 — the driver always gets one JSON line."""
    import sys as _sys
    import traceback as _tb
    try:
        _run()
        return
    except (SystemExit, KeyboardInterrupt):
        raise
    except BaseException:
        _tb.print_exc()
    from ray_tpu.util import hwprobe
    model = os.environ.get("SERVE_MODEL", "gpt2s")
    name = hwprobe.lg_name("SERVE_BENCH", model, "gpt2s")
    if not os.environ.get("SERVE_BENCH_RETRIED"):
        print("serve_bench: run failed; retrying once in a fresh "
              "process", file=_sys.stderr, flush=True)
        os.environ["SERVE_BENCH_RETRIED"] = "1"
        os.execv(_sys.executable,
                 [_sys.executable, os.path.abspath(__file__)])
    print(json.dumps(hwprobe.stale_record(
        name, {"error": "serve bench crashed twice (see stderr)"},
        "fresh serve capture failed twice; emitting last-good")))


def _run() -> None:
    from ray_tpu.util import hwprobe

    model = os.environ.get("SERVE_MODEL", "gpt2s")
    lg_name = hwprobe.lg_name("SERVE_BENCH", model, "gpt2s")

    # Probe in a subprocess before importing jax (see bench.py: two
    # rounds of driver captures died on a wedged tunnel at import).
    hwprobe.ensure_backend(
        lg_name, "fresh serve capture failed: TPU tunnel never initialized")

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    cfg, params, label = _build(model)

    slots = int(os.environ.get("SERVE_SLOTS", 16 if on_tpu else 4))
    chunk = int(os.environ.get("SERVE_CHUNK", 16 if on_tpu else 4))
    depth = int(os.environ.get("SERVE_DEPTH", 4 if on_tpu else 2))
    max_new = int(os.environ.get("SERVE_MAX_NEW",
                                 64 if on_tpu else 8))
    n_requests = 256 if on_tpu else 12

    sweep_on = os.environ.get("SERVE_SWEEP", "").lower() \
        not in ("", "0", "false")
    if sweep_on and on_tpu:
        # Short runs over the grid, then the winner at full length.
        # Slots dominate: tokens/dispatch = slots x chunk and the
        # per-dispatch cost through the tunneled chip is mostly fixed
        # (~30-60 ms), so wider decode batches win until device time
        # passes the link latency (measured: raw piped ceiling 8.2k
        # tok/s at 48x16, falling again by 64x16).
        best, best_cfg = -1.0, None
        grid = [(16, 16, 3), (32, 16, 3), (48, 8, 3), (48, 16, 3),
                (48, 16, 2)]
        sweep_log = []
        for s, c, d in grid:
            r = _run_once(cfg, params, num_slots=s,
                          decode_chunk=c, pipeline_depth=d,
                          max_new=max_new, n_requests=96)
            sweep_log.append({"slots": s, "chunk": c, "depth": d,
                              "tps": r["decode_tokens_per_s"],
                              "ttft_p50_ms": r["ttft_p50_ms"]})
            # Round target: TTFT p50 <= 50 ms at light load.
            if r["decode_tokens_per_s"] > best \
                    and r["ttft_p50_ms"] <= 50.0:
                best, best_cfg = r["decode_tokens_per_s"], (s, c, d)
        if best_cfg is None:                    # nothing met the TTFT bar
            e = max(sweep_log, key=lambda e: e["tps"])
            best_cfg = (e["slots"], e["chunk"], e["depth"])
        slots, chunk, depth = best_cfg
    else:
        sweep_log = None

    r = _run_once(cfg, params, num_slots=slots, decode_chunk=chunk,
                  pipeline_depth=depth, max_new=max_new,
                  n_requests=n_requests)
    out = {
        "metric": "serve_continuous_batching",
        "model": label,
        "device": str(getattr(dev, "device_kind", dev.platform)),
        **r,
        "vs_r02_decode_tps": round(
            r["decode_tokens_per_s"] / 920.0, 2),
    }
    if sweep_log:
        out["sweep"] = sweep_log
    suffix = "" if model == "gpt2s" else f"_{model.replace('-', '_')}"
    rnd = os.environ.get("SERVE_ROUND", "r05")
    if on_tpu:   # never clobber the hardware record with a CPU smoke run
        with open(f"SERVE_BENCH_{rnd}{suffix}.json", "w") as f:
            json.dump(out, f, indent=1)
        hwprobe.record_last_good(lg_name, out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
